"""EpiChord — reactive Chord with a slice-invariant finger cache.

TPU-native rebuild of the reference EpiChord
(src/overlay/epichord/EpiChord.{h,cc} + EpiChordNodeList +
EpiChordFingerCache; params default.ini:144-164: successorListSize 4,
joinDelay 10s, joinRetry 2, stabilizeDelay 20s, cacheFlushDelay 20s,
cacheCheckMultiplier 3, cacheTTL 120s, nodesPerSlice 2, lookupMerge true),
after "EpiChord: Parallelizing the Chord Lookup Algorithm with Reactive
Routing State Management" (Leong/Liskov/Demaine, MIT-LCS-TR-963).

State per node:
  * symmetric neighbor lists — ``succ``/``pred`` [N, S] ring-sorted both
    ways from the own key (EpiChordNodeList);
  * a **finger cache** [N, C] of every node ever observed, with per-entry
    lastUpdate timestamps and TTL expiry (EpiChordFingerCache::
    updateFinger / removeOldFingers).  The cache — not a routing table —
    is the routing state: it is fed reactively by every received call,
    response, FindNode payload, join transfer, and stabilize exchange
    (receiveNewNode, EpiChord.cc:1178-1209).

Protocol:
  * join: iterative lookup of the own key seeded at a bootstrap node,
    then EpiChordJoinCall to the responsible node; the JoinResponse
    transfers succ+pred lists and a cache sample; the joiner becomes
    READY and JoinAcks the responder, which adopts it as predecessor
    (rpcJoin/handleRpcJoinResponse/rpcJoinAck, EpiChord.cc:871-965);
  * stabilize: every stabilizeDelay, one call to pred (type SUCCESSOR)
    and one to succ (type PREDECESSOR), each carrying neighbor additions;
    the callee direct-adds the caller + additions to the matching list
    and responds with its pred+succ lists, which the caller folds into
    the cache (rpcStabilize/handleRpcStabilizeResponse, EpiChord.cc:
    999-1150);
  * cache flush: every cacheFlushDelay expired fingers are dropped; every
    cacheCheckMultiplier-th flush checks the **slice invariant** — the
    ring is divided into exponentially growing slices (me ± max>>offset)
    and any slice not covered by the succ/pred lists must hold
    ≥ nodesPerSlice cache entries, else a lookup to the slice midpoint
    repopulates it (checkCacheInvariant/checkCacheSlice,
    EpiChord.cc:416-516);
  * findNode (EpiChord.cc:517-629): siblings (self+neighbors) when
    responsible; otherwise the directional succ/pred head plus the
    numRedundantNodes cache entries closest at-or-after the key
    clockwise (EpiChordFingerCache::findBestHops lower_bound walk).

Deviations (documented): the cache is bounded at ``cache_size`` with
oldest-lastUpdate eviction (the reference's std::map is unbounded); the
per-entry lastUpdate piggyback ext (EpiChordFindNodeExtMessage) is
dropped — learned fingers are stamped with receive time; stabilize
responses are always "full" (the hasChanged-gated partial response and
the dead-range gossip of the reference are skipped);
FalseNegWarning/stabilizeEstimation/fibonacci-slices are not implemented
(defaults exercise none of the latter two beyond estimation, which only
rescales the stabilize interval).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps.kbrtest import KbrTestApp
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.common import route as rt_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
UMAX = jnp.uint32(0xFFFFFFFF)

DEAD, JOINING, READY = 0, 1, 2
P_JOIN, P_SLICE, P_APP = 1, 2, 3

# stabilize call node types (EpiChordMessage.msg NodeType)
NT_PRED, NT_SUCC = 0, 1


@dataclasses.dataclass(frozen=True)
class EpiChordParams:
    """default.ini:144-164."""

    succ_size: int = 4            # successorListSize (both lists)
    join_delay: float = 10.0
    join_retry: int = 2
    stabilize_delay: float = 20.0
    cache_flush_delay: float = 20.0
    cache_check_mult: int = 3
    cache_ttl: float = 120.0
    nodes_per_slice: int = 2
    redundant_nodes: int = 3      # lookupRedundantNodes
    rpc_timeout: float = 1.5
    # engine-shape knobs
    cache_size: int = 64          # bounded cache (module docstring)
    max_slices: int = 24          # static slice-check unroll
    additions: int = 4            # neighbors piggybacked per stabilize


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EpiChordState:
    state: jnp.ndarray        # [N] i32
    succ: jnp.ndarray         # [N, S] i32 cw-sorted
    pred: jnp.ndarray         # [N, S] i32 ccw-sorted
    cache: jnp.ndarray        # [N, C] i32
    cache_seen: jnp.ndarray   # [N, C] i64 lastUpdate
    t_join: jnp.ndarray       # [N] i64
    join_retry: jnp.ndarray   # [N] i32
    t_stab: jnp.ndarray       # [N] i64
    t_cache: jnp.ndarray      # [N] i64
    check_ctr: jnp.ndarray    # [N] i32
    slice_cursor: jnp.ndarray  # [N] i32 — round-robin deficient slice
    lk: lk_mod.LookupState
    rr: object                # rt_mod.RouteState — recursive-routing hook
    app: object
    app_glob: object


class EpiChordLogic:
    """Engine logic interface (engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: EpiChordParams = EpiChordParams(),
                 lcfg: lk_mod.LookupConfig | None = None,
                 app=None,
                 rcfg: rt_mod.RouteConfig | None = None):
        """``rcfg`` switches the app data path to the recursive family
        (semi/full/source), exactly like chord.py — the generic
        sendToKey machinery serves every overlay in the reference
        (BaseOverlay.cc:1367-1581); wired via common/route.py's shared
        prepass/originate/reroute helpers."""
        self.key_spec = spec
        self.p = params
        self.lcfg = lcfg or lk_mod.LookupConfig(merge=True)
        self.app = app or KbrTestApp()
        self.rcfg = rcfg
        if rcfg is not None and getattr(self.app, "rcfg", "no") is None:
            self.app.rcfg = rcfg
        # EpiChord responsibility: clockwise successor-of-key holds it
        # (chord-family; see chord.py dist_fn note)
        if getattr(self.app, "dist_fn", "no") is None:
            self.app.dist_fn = (
                lambda nk, rk: K.ring_distance(rk, nk, spec))
        # static table: max_key >> o for the slice bounds
        self._shifted_max = jnp.stack(
            [K.shr_const(K.max_key(spec), o, spec)
             for o in range(1, params.max_slices + 3)])

    # -- engine interface ---------------------------------------------------

    def stat_spec(self) -> stats_mod.StatSpec:
        app = self.app.stat_spec()
        return stats_mod.StatSpec(
            scalars=tuple(app["scalars"]) + ("lookup_hops",),
            hists=tuple(app["hists"]),
            counters=tuple(app["counters"]) + (
                "epi_joins", "epi_slice_lookups", "lookup_success",
                "lookup_failed", "route_dropped"),
        )

    def split(self, st: EpiChordState):
        return dataclasses.replace(st, app_glob=None), st.app_glob

    def merge(self, node_part: EpiChordState, glob):
        return dataclasses.replace(node_part, app_glob=glob)

    def post_step(self, ctx, st: EpiChordState, events):
        app, glob = self.app.post_step(ctx, st.app, st.app_glob, events)
        return dataclasses.replace(st, app=app, app_glob=glob)

    def init(self, rng, n: int) -> EpiChordState:
        p = self.p
        return EpiChordState(
            state=jnp.zeros((n,), I32),
            succ=jnp.full((n, p.succ_size), NO_NODE, I32),
            pred=jnp.full((n, p.succ_size), NO_NODE, I32),
            cache=jnp.full((n, p.cache_size), NO_NODE, I32),
            cache_seen=jnp.zeros((n, p.cache_size), I64),
            t_join=jnp.full((n,), T_INF, I64),
            join_retry=jnp.full((n,), p.join_retry, I32),
            t_stab=jnp.full((n,), T_INF, I64),
            t_cache=jnp.full((n,), T_INF, I64),
            check_ctr=jnp.zeros((n,), I32),
            slice_cursor=jnp.zeros((n,), I32),
            lk=jax.vmap(lambda _: lk_mod.init(self.lcfg, self.key_spec.lanes))(
                jnp.arange(n)),
            rr=jax.vmap(lambda _: rt_mod.init(
                self.rcfg or rt_mod.RouteConfig(), self.key_spec.lanes,
                16))(jnp.arange(n)),
            app=self.app.init(n),
            app_glob=self.app.glob_init(rng),
        )

    def reset(self, st: EpiChordState, clear, join, t_now, rng):
        n = st.state.shape[0]
        glob = st.app_glob
        st = dataclasses.replace(st, app_glob=None)
        fresh = dataclasses.replace(self.init(rng, n), app_glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, app_glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: EpiChordState):
        return st.state == READY

    def next_event(self, st: EpiChordState):
        joining = st.state == JOINING
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        t = jnp.minimum(t, jnp.where(ready, st.t_stab, T_INF))
        t = jnp.minimum(t, jnp.where(ready, st.t_cache, T_INF))
        t = jnp.minimum(t, jnp.where(ready, self.app.next_event(st.app),
                                     T_INF))
        t = jnp.minimum(t, jax.vmap(lk_mod.next_event)(st.lk))
        if self.rcfg is not None:
            t = jnp.minimum(t, jax.vmap(rt_mod.next_event)(st.rr))
        return t

    # -- neighbor lists + cache ---------------------------------------------

    def _ring_sorted(self, ctx, me_key, node_idx, cands, clockwise):
        """Top-S unique candidates by cw/ccw ring distance from own key
        (EpiChordNodeList: std::map keyed by directional distance)."""
        s = self.p.succ_size
        ck = ctx.keys[jnp.maximum(cands, 0)]
        bad = (cands == NO_NODE) | (cands == node_idx) | K.dup_mask(cands)
        me_b = jnp.broadcast_to(me_key, ck.shape)
        d = K.sub(ck, me_b, self.key_spec) if clockwise \
            else K.sub(me_b, ck, self.key_spec)
        d = jnp.where(bad[:, None], UMAX, d)
        _, (c_s, bad_s) = K.sort_by_distance(d, (cands, bad.astype(I32)),
                                             approx=True)
        out = jnp.where(bad_s[:s] != 0, NO_NODE, c_s[:s])
        if out.shape[0] < s:
            out = jnp.concatenate(
                [out, jnp.full((s - out.shape[0],), NO_NODE, I32)])
        return out

    def _cache_put(self, st, cands, seen):
        """updateFinger: refresh lastUpdate for known fingers, insert new
        ones, evict the oldest when full (bounded-cache deviation)."""
        cache, cseen = st.cache, st.cache_seen
        cands = jnp.atleast_1d(jnp.asarray(cands, I32))
        seen = jnp.broadcast_to(jnp.asarray(seen, I64), cands.shape)
        match = (cache[:, None] == cands[None, :]) & (
            cands != NO_NODE)[None, :]
        cseen = jnp.maximum(cseen, jnp.max(
            jnp.where(match, seen[None, :], 0), axis=1))
        fresh_mask = (cands != NO_NODE) & ~jnp.any(match, axis=0) \
            & ~K.dup_mask(cands)
        aug = jnp.concatenate([cache, jnp.where(fresh_mask, cands, NO_NODE)])
        aseen = jnp.concatenate([cseen, jnp.where(fresh_mask, seen, 0)])
        # keep the newest C entries (invalid slots sort oldest)
        order = jnp.argsort(  # analysis: allow(sort-call)
            jnp.where(aug == NO_NODE, jnp.int64(-1), aseen))[::-1]
        aug, aseen = aug[order], aseen[order]
        return dataclasses.replace(
            st, cache=aug[:self.p.cache_size],
            cache_seen=jnp.where(aug[:self.p.cache_size] == NO_NODE, 0,
                                 aseen[:self.p.cache_size]))

    def _receive_new_node(self, ctx, st, me_key, node_idx, cands, direct,
                          now):
        """receiveNewNode (EpiChord.cc:1178-1209): cache always; the
        succ/pred lists only for directly observed nodes."""
        st = self._cache_put(st, cands, now)
        cands = jnp.atleast_1d(jnp.asarray(cands, I32))
        if direct:
            st = dataclasses.replace(
                st,
                succ=self._ring_sorted(
                    ctx, me_key, node_idx,
                    jnp.concatenate([st.succ, cands]), True),
                pred=self._ring_sorted(
                    ctx, me_key, node_idx,
                    jnp.concatenate([st.pred, cands]), False))
        return st

    def _expire_cache(self, st, now):
        ttl_ns = jnp.int64(int(self.p.cache_ttl * NS))
        dead = (st.cache != NO_NODE) & (st.cache_seen + ttl_ns < now)
        return dataclasses.replace(
            st,
            cache=jnp.where(dead, NO_NODE, st.cache),
            cache_seen=jnp.where(dead, 0, st.cache_seen))

    def _handle_failed(self, ctx, st, me_key, node_idx, failed, now):
        """Remove failed nodes everywhere; losing the last succ or pred
        while READY → rejoin (handleFailedNode, EpiChord.cc:816-846)."""
        failed = jnp.atleast_1d(failed)
        failed = jnp.where(failed == node_idx, NO_NODE, failed)
        any_failed = jnp.any(failed != NO_NODE)

        def hit(x):
            return (x[..., None] == failed).any(-1) & (x != NO_NODE)

        succ = self._ring_sorted(ctx, me_key, node_idx,
                                 jnp.where(hit(st.succ), NO_NODE, st.succ),
                                 True)
        pred = self._ring_sorted(ctx, me_key, node_idx,
                                 jnp.where(hit(st.pred), NO_NODE, st.pred),
                                 False)
        chit = hit(st.cache)
        st2 = dataclasses.replace(
            st, succ=succ, pred=pred,
            cache=jnp.where(chit, NO_NODE, st.cache),
            cache_seen=jnp.where(chit, 0, st.cache_seen))
        st = select_tree(any_failed, st2, st)
        rejoin = any_failed & (st.state == READY) & (
            (st.succ[0] == NO_NODE) | (st.pred[0] == NO_NODE))
        fresh_lk = lk_mod.init(self.lcfg, self.key_spec.lanes)
        return dataclasses.replace(
            st,
            state=jnp.where(rejoin, JOINING, st.state),
            t_join=jnp.where(rejoin, now, st.t_join),
            t_stab=jnp.where(rejoin, T_INF, st.t_stab),
            t_cache=jnp.where(rejoin, T_INF, st.t_cache),
            lk=select_tree(rejoin, fresh_lk, st.lk),
            app=self.app.on_stop(st.app, rejoin))

    def _become_ready(self, ctx, st, en, now, rng):
        p = self.p
        return dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            t_join=jnp.where(en, T_INF, st.t_join),
            t_stab=jnp.where(en, now + jnp.int64(
                int(p.stabilize_delay * NS)), st.t_stab),
            t_cache=jnp.where(en, now + jnp.int64(
                int(p.cache_flush_delay * NS)), st.t_cache),
            app=self.app.on_ready(st.app, en, now, rng))

    # -- findNode (EpiChord.cc:517-629) -------------------------------------

    def _is_sibling(self, st, ctx, me_key, key):
        pred_ok = st.pred[0] != NO_NODE
        pk = ctx.keys[jnp.maximum(st.pred[0], 0)]
        alone = ~pred_ok & (st.succ[0] == NO_NODE)
        return (st.state == READY) & (
            alone
            | (~pred_ok & K.eq(key, me_key))
            | (pred_ok & K.is_between_r(key, pk, me_key, self.key_spec)))

    def _find_node(self, ctx, st, me_key, node_idx, key, rmax, src):
        """Returns ([rmax] candidates, is_sib).  ``src`` selects the
        directional neighbor per the source-side rule (NO_NODE = local
        request → whichever of succ/pred is closer to the key)."""
        p, spec = self.p, self.key_spec
        is_sib = self._is_sibling(st, ctx, me_key, key)

        # sibling payload: self + pred0 + successor list
        sib_set = jnp.full((rmax,), NO_NODE, I32)
        sib_set = sib_set.at[0].set(node_idx)
        sib_set = sib_set.at[1].set(st.pred[0])
        k = min(p.succ_size, rmax - 2)
        sib_set = sib_set.at[2:2 + k].set(st.succ[:k])

        # directional head
        s0, p0 = st.succ[0], st.pred[0]
        s0k = ctx.keys[jnp.maximum(s0, 0)]
        p0k = ctx.keys[jnp.maximum(p0, 0)]
        src_ok = src != NO_NODE
        srck = ctx.keys[jnp.maximum(src, 0)]
        d_s = K.sub(key, s0k, spec)
        d_p = K.sub(key, p0k, spec)
        local_pick = jnp.where(K.lt(K.sub(s0k, key, spec),
                                    K.sub(key, s0k, spec)), s0, p0)
        # remote: us between source and key → successor side, else pred
        fwd = K.is_between(me_key, srck, key, spec)
        head = jnp.where(src_ok, jnp.where(fwd, s0, p0),
                         jnp.where(K.lt(K.ring_distance(s0k, key, spec),
                                        K.ring_distance(p0k, key, spec))
                                   if False else
                                   K.lt(d_s, d_p), s0, p0))

        # findBestHops: cache entries at-or-after the key clockwise
        # (lower_bound walk over the cw-from-me keyed map)
        cands = jnp.concatenate([st.cache, st.succ, st.pred])
        ck = ctx.keys[jnp.maximum(cands, 0)]
        bad = (cands == NO_NODE) | (cands == node_idx) | (
            src_ok & (cands == src)) | (cands == head) | K.dup_mask(cands)
        d = K.sub(ck, jnp.broadcast_to(key, ck.shape), spec)  # cw key→cand
        d = jnp.where(bad[:, None], UMAX, d)
        _, (c_s,) = K.sort_by_distance(d, (cands,), approx=True)
        res = jnp.full((rmax,), NO_NODE, I32)
        res = res.at[0].set(jnp.where(head != NO_NODE, head, c_s[0]))
        take = min(p.redundant_nodes, rmax - 1)
        res = res.at[1:1 + take].set(c_s[:take])
        res = jnp.where(st.state == READY, res, NO_NODE)
        return jnp.where(is_sib, sib_set, res), is_sib

    # -- the per-node step ---------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, lcfg, spec = self.p, self.lcfg, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rngs = jax.random.split(rng, 8)
        t0 = ctx.t_start
        t_end = ctx.t_end
        S = p.succ_size

        def metric_fn(cand_slots, target):
            # frontier sorted by how far past the key a candidate sits
            # (candidates are successor-side, EpiChordIterativeLookup)
            ck = ctx.keys[jnp.maximum(cand_slots, 0)]
            return K.sub(ck, jnp.broadcast_to(target, ck.shape), spec)

        ev = app_base.AppEvents()
        joins_cnt = jnp.int32(0)
        slice_cnt = jnp.int32(0)
        anyfail_cnt = jnp.int32(0)
        lksucc_cnt = jnp.int32(0)

        def pad_nodes(vec):
            out = jnp.full((rmax,), NO_NODE, I32)
            k = min(vec.shape[0], rmax)
            return out.at[:k].set(vec[:k])

        routedrop_cnt = jnp.int32(0)
        # recursive-route pre-pass (shared helpers, common/route.py):
        # forward-or-decapsulate KBR_ROUTE wrappers BEFORE the per-slot
        # dispatch below, driven by this overlay's own findNode
        if self.rcfg is not None:
            res_rt, sib_rt = jax.vmap(
                lambda kk, ss: self._find_node(ctx, st, me_key, node_idx,
                                               kk, rmax, ss))(
                msgs.key, msgs.src)
            veto = ((lambda mm: self.app.forward(st.app, mm, ctx))
                    if hasattr(self.app, "forward") else None)
            new_rr, msgs, drop = rt_mod.prepass(
                st.rr, ob, msgs, res_rt, sib_rt, st.state == READY,
                node_idx, self.rcfg, forward_veto=veto)
            st = dataclasses.replace(st, rr=new_rr)
            routedrop_cnt += drop

        # ------------------------------------------------------- inbox -----
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid

            # every inbound call/response feeds the cache + lists
            # (handleRpcCall/handleRpcResponse receiveNewNode direct).
            # READY-gated: a joining node never emits RPCs in the
            # reference (its JoinCall is proxy-routed via the bootstrap,
            # EpiChord.cc:309-337), so joiners must not enter routing
            # state or lookups forward into non-answering nodes.
            # Protocol-explicit adds (JoinAck, stabilize additions)
            # below stay ungated.
            st = select_tree(
                v & ctx.ready[jnp.maximum(m.src, 0)],
                self._receive_new_node(ctx, st, me_key, node_idx, m.src,
                                       True, now), st)

            # FindNodeCall
            en = v & (m.kind == wire.FINDNODE_CALL)
            res, sib = self._find_node(ctx, st, me_key, node_idx, m.key,
                                       rmax, m.src)
            n_res = jnp.sum((res != NO_NODE).astype(I32))
            ob.send(en & (st.state == READY), now, m.src, wire.FINDNODE_RES,
                    key=m.key, a=m.a, b=m.b, c=sib.astype(I32), nodes=res,
                    size_b=wire.BASE_CALL_B + 1 + wire.NODEHANDLE_B * n_res)

            # FindNodeResponse → lookup engine + cache learning
            en = v & (m.kind == wire.FINDNODE_RES)
            st = dataclasses.replace(st, lk=lk_mod.on_response(
                st.lk, dataclasses.replace(m, valid=en), metric_fn, lcfg))
            learned = m.nodes[:lcfg.frontier]
            l_ok = (learned != NO_NODE) & ctx.ready[jnp.maximum(learned, 0)]
            st = select_tree(
                en, self._cache_put(st, jnp.where(l_ok, learned, NO_NODE),
                                    now), st)

            # JoinCall → transfer lists + cache sample (rpcJoin)
            en = v & (m.kind == wire.EPI_JOIN_CALL) & (st.state == READY)
            n_cache = max(0, rmax - 2 * S)
            payload = jnp.concatenate(
                [st.pred, st.succ, st.cache[:n_cache]])
            ob.send(en, now, m.src, wire.EPI_JOIN_RES, a=jnp.int32(S),
                    nodes=pad_nodes(payload),
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B * rmax)

            # JoinResponse (handleRpcJoinResponse): adopt lists, READY,
            # ack the responder
            en = v & (m.kind == wire.EPI_JOIN_RES) & (st.state == JOINING)
            preds = m.nodes[:S]
            succs = m.nodes[S:2 * S]
            cache_x = m.nodes[2 * S:]
            new_succ = self._ring_sorted(
                ctx, me_key, node_idx,
                jnp.concatenate([st.succ, succs, m.src[None]]), True)
            new_pred = self._ring_sorted(
                ctx, me_key, node_idx,
                jnp.concatenate([st.pred, preds, m.src[None]]), False)
            st = dataclasses.replace(
                st,
                succ=jnp.where(en, new_succ, st.succ),
                pred=jnp.where(en, new_pred, st.pred))
            st = select_tree(en, self._cache_put(st, cache_x, now), st)
            joins_cnt += en.astype(I32)
            st = self._become_ready(ctx, st, en, now, rngs[0])
            ob.send(en, now, m.src, wire.EPI_JOINACK_CALL,
                    size_b=wire.BASE_CALL_B)

            # JoinAck (rpcJoinAck): the joiner becomes our predecessor
            en = v & (m.kind == wire.EPI_JOINACK_CALL) & (
                st.state == READY)
            st = dataclasses.replace(
                st,
                pred=jnp.where(en, self._ring_sorted(
                    ctx, me_key, node_idx,
                    jnp.concatenate([st.pred, m.src[None]]), False),
                    st.pred),
                succ=jnp.where(en & (st.succ[0] == NO_NODE),
                               st.succ.at[0].set(m.src), st.succ))

            # StabilizeCall (rpcStabilize): direct-add requestor +
            # additions to the matching list; respond with pred++succ
            en = v & (m.kind == wire.EPI_STAB_CALL) & (st.state == READY)
            adds = jnp.concatenate([m.src[None], m.nodes[:p.additions]])
            from_pred = m.a == NT_PRED
            st = dataclasses.replace(
                st,
                pred=jnp.where(en & from_pred, self._ring_sorted(
                    ctx, me_key, node_idx,
                    jnp.concatenate([st.pred, adds]), False), st.pred),
                succ=jnp.where(en & ~from_pred, self._ring_sorted(
                    ctx, me_key, node_idx,
                    jnp.concatenate([st.succ, adds]), True), st.succ))
            ob.send(en, now, m.src, wire.EPI_STAB_RES, a=jnp.int32(S),
                    nodes=pad_nodes(jnp.concatenate([st.pred, st.succ])),
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B * 2 * S)

            # StabilizeResponse → cache only (handleRpcStabilizeResponse)
            en = v & (m.kind == wire.EPI_STAB_RES) & (st.state == READY)
            learned = m.nodes[:2 * S]
            s_ok = (learned != NO_NODE) & ctx.ready[jnp.maximum(learned, 0)]
            st = select_tree(
                en, self._cache_put(st, jnp.where(s_ok, learned, NO_NODE),
                                    now), st)

            # app-owned kinds
            sib_app = self._is_sibling(st, ctx, me_key, m.key)
            st = dataclasses.replace(st, app=self.app.on_msg(
                st.app, m, ctx, ob, ev, sib_app))

            # pings
            ob.send(v & (m.kind == wire.PING_CALL), now, m.src,
                    wire.PING_RES, a=m.a, size_b=wire.BASE_CALL_B)

        # ------------------------------------------------------- timers ----
        # join (handleJoinTimerExpired: routed JoinCall via bootstrap →
        # here a lookup for the own key, then a direct JoinCall)
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        no_join_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_JOIN))
        alone = en_j & (boot == NO_NODE)
        joins_cnt += alone.astype(I32)
        st = self._become_ready(ctx, st, alone, now_j, rngs[2])
        slot, have = lk_mod.free_slot(st.lk)
        start_join = en_j & (boot != NO_NODE) & no_join_lk & have
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(boot)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_join, slot, P_JOIN, 0, me_key, seed, now_j, lcfg))
        st = dataclasses.replace(st, t_join=jnp.where(
            en_j & ~alone, now_j + jnp.int64(int(p.join_delay * NS)),
            st.t_join))

        # stabilize (handleStabilizeTimerExpired): one call each way
        en_s = (st.state == READY) & (st.t_stab < t_end)
        now_s = jnp.maximum(st.t_stab, t0)
        adds_s = pad_nodes(st.succ[:p.additions])
        adds_p = pad_nodes(st.pred[:p.additions])
        ob.send(en_s & (st.pred[0] != NO_NODE), now_s, st.pred[0],
                wire.EPI_STAB_CALL, a=jnp.int32(NT_SUCC), nodes=adds_s,
                size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B * p.additions)
        ob.send(en_s & (st.succ[0] != NO_NODE), now_s, st.succ[0],
                wire.EPI_STAB_CALL, a=jnp.int32(NT_PRED), nodes=adds_p,
                size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B * p.additions)
        st = dataclasses.replace(st, t_stab=jnp.where(
            en_s, now_s + jnp.int64(int(p.stabilize_delay * NS)),
            st.t_stab))

        # cache flush + slice invariant (handleCacheFlushTimerExpired)
        en_c = (st.state == READY) & (st.t_cache < t_end)
        now_c = jnp.maximum(st.t_cache, t0)
        st = select_tree(en_c, self._expire_cache(st, now_c), st)
        ctr = jnp.where(en_c, st.check_ctr + 1, st.check_ctr)
        do_check = en_c & (ctr > p.cache_check_mult)
        ctr = jnp.where(do_check, 0, ctr)
        st = dataclasses.replace(
            st, check_ctr=ctr,
            t_cache=jnp.where(en_c, now_c + jnp.int64(
                int(p.cache_flush_delay * NS)), st.t_cache))

        # slice check (checkCacheInvariant, non-fibonacci): find deficient
        # slices on both sides, start ONE midpoint lookup per check
        # (round-robin cursor; the reference fires one per slice)
        lists_full = (st.succ[-1] != NO_NODE) & (st.pred[-1] != NO_NODE)
        lastsk = ctx.keys[jnp.maximum(st.succ[-1], 0)]
        lastpk = ctx.keys[jnp.maximum(st.pred[-1], 0)]
        cachek = ctx.keys[jnp.maximum(st.cache, 0)]
        cache_ok = st.cache != NO_NODE
        deficient = []
        targets = []
        for o in range(p.max_slices):
            far_s = K.add(me_key, self._shifted_max[o], spec)
            near_s = K.add(me_key, self._shifted_max[o + 1], spec)
            act_s = K.is_between(lastsk, me_key, near_s, spec)
            n_in = jnp.sum((cache_ok & K.is_between_r(
                cachek, jnp.broadcast_to(near_s, cachek.shape),
                jnp.broadcast_to(far_s, cachek.shape), spec)).astype(I32))
            mid_s = K.add(near_s, K.shr_const(
                K.sub(far_s, near_s, spec), 1, spec), spec)
            deficient.append(act_s & (n_in < p.nodes_per_slice))
            targets.append(mid_s)
            far_p = K.sub(me_key, self._shifted_max[o], spec)
            near_p = K.sub(me_key, self._shifted_max[o + 1], spec)
            act_p = K.is_between(lastpk, near_p, me_key, spec)
            n_in_p = jnp.sum((cache_ok & K.is_between_r(
                cachek, jnp.broadcast_to(far_p, cachek.shape),
                jnp.broadcast_to(near_p, cachek.shape), spec)).astype(I32))
            mid_p = K.add(far_p, K.shr_const(
                K.sub(near_p, far_p, spec), 1, spec), spec)
            deficient.append(act_p & (n_in_p < p.nodes_per_slice))
            targets.append(mid_p)
        deficient = jnp.stack(deficient)          # [2*O]
        targets = jnp.stack(targets)              # [2*O, KL]
        nsl = deficient.shape[0]
        rot = (jnp.arange(nsl, dtype=I32) + st.slice_cursor) % nsl
        pick_rot = jnp.argmax(deficient[rot]).astype(I32)
        pick = rot[pick_rot]
        any_def = jnp.any(deficient)
        tgt = targets[pick]
        no_slice_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_SLICE))
        seed_s, sib_s = self._find_node(ctx, st, me_key, node_idx, tgt,
                                        rmax, NO_NODE)
        slot, have = lk_mod.free_slot(st.lk)
        start_slice = do_check & lists_full & any_def & no_slice_lk \
            & have & ~sib_s & (seed_s[0] != NO_NODE)
        slice_cnt += start_slice.astype(I32)
        st = dataclasses.replace(
            st,
            slice_cursor=jnp.where(do_check, pick + 1, st.slice_cursor),
            lk=lk_mod.start(st.lk, start_slice, slot, P_SLICE, 0, tgt,
                            seed_s[:lcfg.frontier], now_c, lcfg))

        # app timer
        # graceful-leave: hand app data to the successor and stop
        # firing app tests during the grace window (apps/base.py on_leave)
        st = dataclasses.replace(st, app=app_base.leave_protocol(
            self.app, st.app, ctx, ob, ev, t0, node_idx, st.succ[0],
            st.state == READY))
        en_a = (st.state == READY) & (
            self.app.next_event(st.app) < t_end)
        now_a = jnp.maximum(self.app.next_event(st.app), t0)
        app, req = self.app.on_timer(st.app, en_a, ctx, now_a, rngs[3], ev, node_idx)
        st = dataclasses.replace(st, app=app)
        seed_a, sib_a = self._find_node(ctx, st, me_key, node_idx, req.key,
                                        rmax, NO_NODE)
        local = req.want & sib_a
        res_local = seed_a[:lcfg.frontier]
        slot, have = lk_mod.free_slot(st.lk)
        if self.rcfg is not None and hasattr(self.app, "route_policy"):
            new_rr, new_app, route_fire, start_app = rt_mod.originate(
                st.rr, ob, self.app, st.app, req, seed_a[0], sib_a, have,
                now_a, node_idx, rmax, self.rcfg, ctx.measuring)
            st = dataclasses.replace(st, rr=new_rr, app=new_app)
        else:
            route_fire = jnp.bool_(False)
            start_app = req.want & ~sib_a & have & (seed_a[0] != NO_NODE)
        insta_fail = req.want & ~sib_a & ~start_app & ~route_fire
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=local | insta_fail, success=local, tag=req.tag,
                target=req.key,
                results=jnp.where(local, res_local, NO_NODE),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_app, slot, P_APP, req.tag, req.key,
            seed_a[:lcfg.frontier], now_a, lcfg))

        # ------------------------------------------------ lookup timeouts --
        new_lk, failed_nodes, _ = lk_mod.on_timeouts(st.lk, t_end, t0, lcfg)
        st = dataclasses.replace(st, lk=new_lk)
        st = self._handle_failed(ctx, st, me_key, node_idx, failed_nodes,
                                 t0)

        # route-hop ACK timeouts → handleFailedNode + reroute parked
        # messages around the failed hop (shared helper)
        if self.rcfg is not None:
            new_rr, rt_failed, rt_retry = rt_mod.on_timeouts(
                st.rr, t_end, self.rcfg)
            st = dataclasses.replace(st, rr=new_rr)
            st = self._handle_failed(ctx, st, me_key, node_idx, rt_failed,
                                     t0)
            res_q, sib_q = jax.vmap(
                lambda kk: self._find_node(ctx, st, me_key, node_idx, kk,
                                           rmax, NO_NODE))(st.rr.key)
            new_rr, drop_q = rt_mod.reroute(
                st.rr, ob, res_q, sib_q, rt_failed, rt_retry, t0,
                node_idx, self.rcfg)
            st = dataclasses.replace(st, rr=new_rr)
            routedrop_cnt += drop_q

        # ------------------------------------------------- completions -----
        new_lk, comp = lk_mod.take_completions(st.lk, t_end)
        st = dataclasses.replace(st, lk=new_lk)
        comp_hops_ev = (comp["hops"].astype(jnp.float32),
                        comp["taken"] & comp["success"])
        for li in range(lcfg.slots):
            en = comp["taken"][li]
            suc = comp["success"][li] & (comp["result"][li] != NO_NODE)
            res = comp["result"][li]
            pur = comp["purpose"][li]
            lksucc_cnt += (en & suc).astype(I32)
            anyfail_cnt += (en & ~suc).astype(I32)

            # join lookup done → JoinCall to the responsible node
            enj = en & (pur == P_JOIN) & (st.state == JOINING)
            ob.send(enj & suc, t0, res, wire.EPI_JOIN_CALL,
                    size_b=wire.BASE_CALL_B)
            # failure → retry handled by t_join periodic refire

            # app lookup → app completion hook
            ena = en & (pur == P_APP)
            st = dataclasses.replace(st, app=self.app.on_lookup_done(
                st.app, app_base.LookupDone(
                    en=ena, success=ena & suc, tag=comp["aux"][li],
                    target=comp["target"][li], results=comp["results"][li],
                    hops=comp["hops"][li], t0=comp["t0"][li]),
                ctx, ob, ev, t0, node_idx))

        # ------------------------------------------------------- pump ------
        new_lk, _ = lk_mod.pump(st.lk, ob, ctx, node_idx, t0, rngs[6], lcfg,
                                num_redundant=p.redundant_nodes)
        st = dataclasses.replace(st, lk=new_lk)

        # ------------------------------------------------------ events -----
        events = {
            "c:epi_joins": joins_cnt,
            "c:epi_slice_lookups": slice_cnt,
            "c:lookup_success": lksucc_cnt,
            "c:lookup_failed": anyfail_cnt,
            "c:route_dropped": routedrop_cnt,
            "s:lookup_hops": comp_hops_ev,
        }
        ev.finish(events, self.app.hist_map)
        return st, ob, events
