"""NICE — hierarchical cluster-based application-layer multicast.

TPU-native rebuild of src/overlay/nice/ (Nice.{h,cc} 3.8k LoC; the
SIGCOMM'02 "Scalable Application Layer Multicast" protocol): nodes form
layered clusters of size k..3k-1 (Nice.h:157 `k`, default.ini:363 k=3);
every cluster elects a leader which is also a member of the next layer
up, so layer membership is a prefix 0..h and leaders form the multicast
backbone.  Data sent into any cluster is re-forwarded by each receiver
into every OTHER cluster it belongs to (Nice.cc:1385
handleNiceMulticast), flooding the whole hierarchy in O(log N) cluster
hops.

Redesigned for the vectorized engine as structure-of-arrays state:

  * cluster membership is a dense [N, LMAX, CMAX] member table plus a
    [N, LMAX] in-layer prefix mask — no per-cluster heap objects
    (NiceCluster.h std::set) and no gate messages;
  * the rendezvous point (Nice.h:105 RendevouzPoint) is an elected
    global scalar maintained by the un-vmapped post_step (LogicBase
    discipline) instead of a configured static address: the
    lowest-slot READY node is RP, and nodes that lose their cluster
    re-join through it (the reference's rpPollTimer partition healing,
    Nice.cc:1478 handleNicePollRp);
  * the join descent (BasicJoinLayer/Query/QueryResponse,
    Nice.cc:555-622,1506) keeps the reference's RTT-probe shape:
    QUERY(layer) returns the responder's cluster members, the joiner
    probes them (handleNiceJoineval echo, Nice.cc:1348-1383), picks the
    nearest and descends until the target layer's leader admits it
    (JoinCluster, Nice.cc:1670);
  * heartbeats (sendHeartbeats, Nice.cc:1757) are member HBs for
    liveness plus authoritative LEADER_HB member lists (the reference's
    NiceLeaderHeartbeat with membership piggyback); eviction after
    peerTimeoutHeartbeats missed intervals (cleanPeers, Nice.cc:2150);
  * maintenance (Nice.cc:2352): leaders split clusters larger than
    3k-1 (ClusterSplit :2621 — the reference minimizes cluster radii
    over all member bipartitions via combination.h, which needs the
    full pairwise-RTT matrix; here the split is a deterministic
    balanced bipartition in slot order — same size invariants, no
    pairwise-RTT state) and merge clusters smaller than k into a
    sibling leader's cluster (ClusterMerge :2866);
  * the ALMTest-style workload (publish into all own clusters, count
    deliveries/dups — src/applications/almtest/ALMTest.cc) is folded
    into the logic like GIA's search app, since multicast group = the
    whole overlay in NICE.

Omitted vs the reference (which itself ships !WORK_IN_PROGRESS!): the
graph-center leader-refinement heuristic (CLUSTERLEADERBOUND transfer,
Nice.cc:2456-2618) — it needs the continuous pairwise-RTT estimates the
scalar build piggybacks on every heartbeat; structural invariants and
dissemination do not depend on it.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
BIG = jnp.int32(2**30)

DEAD, JOINING, READY = 0, 1, 2

# join-descent stages
J_IDLE, J_QUERY, J_PROBE, J_JOIN = 0, 1, 2, 3

NICE_QUERY = 110       # a=layer (-1 = your top layer)
NICE_QUERY_RES = 111   # a=layer, b=cluster leader, nodes=members
NICE_PROBE = 112       # RTT probe (stamp echoed back)
NICE_PROBE_RES = 113
NICE_JOIN = 114        # a=layer — admit me to your layer-a cluster
NICE_JOIN_ACK = 115    # a=layer, nodes=members
NICE_HB = 116          # a=layer — member liveness heartbeat
NICE_LEADER_HB = 117   # a=layer, nodes=authoritative member list
NICE_SPLIT = 118       # a=layer, b=new leader, c=upper anchor, nodes=half
NICE_MERGE = 119       # a=layer, nodes=members to absorb
NICE_MCAST = 120       # a=cluster layer, b=seq, c=origin


@dataclasses.dataclass(frozen=True)
class NiceParams:
    """Reference defaults: default.ini:357-366."""

    k: int = 3                      # cluster parameter
    layers: int = 4                 # maxLayers (Nice.h:62 uses 10; 4 covers
                                    # (3k)^4 ≈ 6.5k nodes at k=3)
    hb_interval: float = 5.0        # heartbeatInterval
    maint_interval: float = 3.3     # maintenanceInterval
    query_interval: float = 2.0     # queryInterval (join retry)
    probe_wait: float = 1.0         # RTT-eval window (query_compare gate)
    peer_timeout_hbs: float = 3.0   # peerTimeoutHeartbeats
    join_delay: float = 1.0
    pub_interval: float = 20.0      # ALMTest sender period
    seen: int = 16                  # duplicate-suppression ring size

    @property
    def cmax(self) -> int:
        return 3 * self.k + 2       # split fires at >3k-1; +2 admit slack


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class NiceState:
    """[N, ...] at rest; step() sees one node's slice (no leading N)."""

    state: jnp.ndarray       # [N] DEAD/JOINING/READY
    in_layer: jnp.ndarray    # [N, LMAX] bool (prefix mask)
    leader: jnp.ndarray      # [N, LMAX] i32 — my cluster's leader
    member: jnp.ndarray      # [N, LMAX, CMAX] i32 — my cluster view (incl self)
    hb_seen: jnp.ndarray     # [N, LMAX, CMAX] i64 — last HB per member
    t_hb: jnp.ndarray        # [N] i64
    t_maint: jnp.ndarray     # [N] i64
    t_pub: jnp.ndarray       # [N] i64 — ALM workload sender
    # join/rejoin descent
    jn_stage: jnp.ndarray    # [N] i32 J_*
    jn_layer: jnp.ndarray    # [N] i32 — layer of the cluster being probed
    jn_target: jnp.ndarray   # [N] i32 — layer we want to join
    jn_cands: jnp.ndarray    # [N, CMAX] i32
    jn_rtt: jnp.ndarray      # [N, CMAX] i64
    jn_sent: jnp.ndarray     # [N] bool — probes fired for this round
    jn_deadline: jnp.ndarray  # [N] i64
    # ALM workload
    seq: jnp.ndarray         # [N] i32 publish counter
    seen: jnp.ndarray        # [N, S] i64 (origin<<32 | seq) dup ring
    seen_n: jnp.ndarray      # [N] i32
    fw_h: jnp.ndarray        # [N] i64 — pending forward (hash; 0 = none)
    fw_src: jnp.ndarray      # [N] i32
    fw_origin: jnp.ndarray   # [N] i32
    fw_seq: jnp.ndarray      # [N] i32
    fw_layer: jnp.ndarray    # [N] i32 — arrival layer (-1 = own publish)
    fw_hops: jnp.ndarray     # [N] i32
    rp: object               # glob: i32 scalar — elected rendezvous point


class NiceLogic:
    """Engine logic (interface: engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: NiceParams = NiceParams()):
        self.key_spec = spec
        self.p = params

    def stat_spec(self):
        return stats_mod.StatSpec(
            scalars=("nice_hops", "nice_layers"),
            hists=(),
            counters=("nice_joins", "nice_pub", "nice_recv", "nice_dup",
                      "nice_splits", "nice_merges", "nice_evicts",
                      "nice_fwd_drop"))

    # ------------------------------------------------ LogicBase glue ---
    def split(self, st):
        return dataclasses.replace(st, rp=None), st.rp

    def merge(self, node_part, glob):
        return dataclasses.replace(node_part, rp=glob)

    def post_step(self, ctx, st, events):
        del events
        ready = (st.state == READY) & ctx.alive
        rp = st.rp
        ok = (rp != NO_NODE) & ready[jnp.maximum(rp, 0)]
        fallback = jnp.where(jnp.any(ready),
                             jnp.argmax(ready).astype(I32), NO_NODE)
        return dataclasses.replace(st, rp=jnp.where(ok, rp, fallback))

    # ------------------------------------------------ engine hooks -----
    def init(self, rng, n: int) -> NiceState:
        p = self.p
        l, c = p.layers, p.cmax
        return NiceState(
            state=jnp.zeros((n,), I32),
            in_layer=jnp.zeros((n, l), bool),
            leader=jnp.full((n, l), NO_NODE, I32),
            member=jnp.full((n, l, c), NO_NODE, I32),
            hb_seen=jnp.zeros((n, l, c), I64),
            t_hb=jnp.full((n,), T_INF, I64),
            t_maint=jnp.full((n,), T_INF, I64),
            t_pub=jnp.full((n,), T_INF, I64),
            jn_stage=jnp.zeros((n,), I32),
            jn_layer=jnp.zeros((n,), I32),
            jn_target=jnp.zeros((n,), I32),
            jn_cands=jnp.full((n, c), NO_NODE, I32),
            jn_rtt=jnp.full((n, c), T_INF, I64),
            jn_sent=jnp.zeros((n,), bool),
            jn_deadline=jnp.full((n,), T_INF, I64),
            seq=jnp.zeros((n,), I32),
            seen=jnp.zeros((n, p.seen), I64),
            seen_n=jnp.zeros((n,), I32),
            fw_h=jnp.zeros((n,), I64),
            fw_src=jnp.full((n,), NO_NODE, I32),
            fw_origin=jnp.full((n,), NO_NODE, I32),
            fw_seq=jnp.zeros((n,), I32),
            fw_layer=jnp.zeros((n,), I32),
            fw_hops=jnp.zeros((n,), I32),
            rp=NO_NODE)

    def reset(self, st, clear, join, t_now, rng):
        n = st.state.shape[0]
        glob = st.rp
        st = dataclasses.replace(st, rp=None)
        fresh = dataclasses.replace(self.init(rng, n), rp=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, rp=glob)
        jitter = (jax.random.uniform(rng, (n,)) *
                  self.p.join_delay * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            jn_stage=jnp.where(join, J_IDLE, st.jn_stage),
            jn_target=jnp.where(join, 0, st.jn_target),
            jn_deadline=jnp.where(join, t_now + jitter, st.jn_deadline))

    def ready_mask(self, st):
        return st.state == READY

    def next_event(self, st):
        ready = st.state == READY
        t = jnp.where(st.state == JOINING, st.jn_deadline, T_INF)
        t = jnp.minimum(t, jnp.where(ready, st.jn_deadline, T_INF))
        t = jnp.minimum(t, jnp.where(ready, st.t_hb, T_INF))
        t = jnp.minimum(t, jnp.where(ready, st.t_maint, T_INF))
        t = jnp.minimum(t, jnp.where(ready, st.t_pub, T_INF))
        # a pending forward / unsent probe round must run this tick
        t = jnp.where((st.fw_h != 0) |
                      ((st.jn_stage == J_PROBE) & ~st.jn_sent),
                      jnp.int64(0), t)
        return t

    # ------------------------------------------------ helpers ----------
    def _become_root(self, st, en, now, node_idx):
        """First node (or healed partition head): single-member layer 0."""
        p = self.p
        mem0 = jnp.full((p.cmax,), NO_NODE, I32).at[0].set(node_idx)
        row = jnp.where(en, 0, p.layers)
        return dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            in_layer=st.in_layer.at[row].set(True, mode="drop"),
            leader=st.leader.at[row].set(node_idx, mode="drop"),
            member=st.member.at[row].set(mem0, mode="drop"),
            jn_stage=jnp.where(en, J_IDLE, st.jn_stage),
            jn_deadline=jnp.where(en, T_INF, st.jn_deadline),
            t_hb=jnp.where(en, now + jnp.int64(int(p.hb_interval * NS)),
                           st.t_hb),
            t_maint=jnp.where(
                en, now + jnp.int64(int(p.maint_interval * NS)),
                st.t_maint),
            t_pub=jnp.where(en, now + jnp.int64(int(p.pub_interval * NS)),
                            st.t_pub))

    def _seen_push(self, st, en, h):
        col = st.seen_n % st.seen.shape[-1]
        return dataclasses.replace(
            st,
            seen=st.seen.at[jnp.where(en, col, st.seen.shape[-1])].set(
                h, mode="drop"),
            seen_n=st.seen_n + en.astype(I32))

    # ------------------------------------------------ the step ---------
    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, spec = self.p, self.key_spec
        lmax, cmax = p.layers, p.cmax
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        del rng
        t0, t_end = ctx.t_start, ctx.t_end
        ev = app_base.AppEvents()
        layer_idx = jnp.arange(lmax, dtype=I32)
        c_joins = jnp.int32(0)
        c_pub = jnp.int32(0)
        c_recv = jnp.int32(0)
        c_dup = jnp.int32(0)
        c_splits = jnp.int32(0)
        c_merges = jnp.int32(0)
        c_evicts = jnp.int32(0)
        c_fwdrop = jnp.int32(0)
        hb_ns = jnp.int64(int(p.hb_interval * NS))
        list_b = 16 + 25 * cmax   # NODEHANDLE_B * cmax payload

        # ========================================= inbox handlers ======
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid
            is_ready = st.state == READY

            # ---- QUERY: return my layer-a cluster (a=-1 → my top) ----
            en = v & (m.kind == NICE_QUERY) & is_ready
            h = jnp.max(jnp.where(st.in_layer, layer_idx, -1))
            l_eff = jnp.clip(jnp.where(m.a < 0, h, jnp.minimum(m.a, h)),
                             0, lmax - 1)
            ob.send(en & (h >= 0), now, m.src, NICE_QUERY_RES,
                    a=l_eff, b=st.leader[l_eff], nodes=st.member[l_eff],
                    size_b=list_b)

            # ---- QUERY_RES: descend or converge --------------------
            en = v & (m.kind == NICE_QUERY_RES) & (st.jn_stage == J_QUERY)
            at_target = en & (m.a <= st.jn_target) & (m.b != NO_NODE)
            # target layer reached: ask the actual leader to admit us
            ob.send(at_target, now, jnp.maximum(m.b, 0), NICE_JOIN,
                    a=st.jn_target, size_b=16)
            descend = en & ~at_target
            st = dataclasses.replace(
                st,
                jn_stage=jnp.where(at_target, J_JOIN,
                                   jnp.where(descend, J_PROBE,
                                             st.jn_stage)),
                jn_layer=jnp.where(descend, m.a, st.jn_layer),
                jn_cands=jnp.where(descend, m.nodes[:cmax], st.jn_cands),
                jn_rtt=jnp.where(descend, T_INF, st.jn_rtt),
                jn_sent=jnp.where(descend, False, st.jn_sent),
                jn_deadline=jnp.where(
                    at_target,
                    now + jnp.int64(int(p.query_interval * NS)),
                    st.jn_deadline))

            # ---- PROBE: echo for RTT measurement -------------------
            en = v & (m.kind == NICE_PROBE)
            ob.send(en, now, m.src, NICE_PROBE_RES, stamp=m.stamp,
                    size_b=8)

            en = v & (m.kind == NICE_PROBE_RES) & (st.jn_stage == J_PROBE)
            hit = en & jnp.any(st.jn_cands == m.src)
            ci = jnp.argmax(st.jn_cands == m.src).astype(I32)
            st = dataclasses.replace(st, jn_rtt=st.jn_rtt.at[
                jnp.where(hit, ci, cmax)].set(now - m.stamp, mode="drop"))

            # ---- JOIN: leader admits a member ----------------------
            l = jnp.clip(m.a, 0, lmax - 1)
            en = (v & (m.kind == NICE_JOIN) & is_ready &
                  st.in_layer[l] & (st.leader[l] == node_idx))
            mem = st.member[l]
            have = jnp.any(mem == m.src)
            slot = jnp.where(have, jnp.argmax(mem == m.src),
                             jnp.argmax(mem == NO_NODE)).astype(I32)
            adm = en & (have | jnp.any(mem == NO_NODE))
            row = jnp.where(adm, l, lmax)
            st = dataclasses.replace(
                st,
                member=st.member.at[row, slot].set(m.src, mode="drop"),
                hb_seen=st.hb_seen.at[row, slot].set(now, mode="drop"))
            ob.send(adm, now, m.src, NICE_JOIN_ACK, a=l,
                    nodes=st.member[l], size_b=list_b)

            # ---- JOIN_ACK: we are in -------------------------------
            l = jnp.clip(m.a, 0, lmax - 1)
            en = v & (m.kind == NICE_JOIN_ACK) & (st.jn_stage == J_JOIN)
            c_joins += (en & (st.state == JOINING)).astype(I32)
            row = jnp.where(en, l, lmax)
            now_row = jnp.zeros((cmax,), I64) + now
            st = dataclasses.replace(
                st,
                in_layer=st.in_layer.at[row].set(True, mode="drop"),
                leader=st.leader.at[row].set(m.src, mode="drop"),
                member=st.member.at[row].set(m.nodes[:cmax], mode="drop"),
                hb_seen=st.hb_seen.at[row].set(now_row, mode="drop"),
                jn_stage=jnp.where(en, J_IDLE, st.jn_stage),
                jn_target=jnp.where(en, 0, st.jn_target),
                jn_deadline=jnp.where(en, T_INF, st.jn_deadline),
                state=jnp.where(en, READY, st.state),
                t_hb=jnp.where(en & (st.t_hb == T_INF), now + hb_ns,
                               st.t_hb),
                t_maint=jnp.where(
                    en & (st.t_maint == T_INF),
                    now + jnp.int64(int(p.maint_interval * NS)),
                    st.t_maint),
                t_pub=jnp.where(
                    en & (st.t_pub == T_INF),
                    now + jnp.int64(int(p.pub_interval * NS)), st.t_pub))

            # ---- HB: member liveness -------------------------------
            l = jnp.clip(m.a, 0, lmax - 1)
            en = v & (m.kind == NICE_HB) & is_ready & st.in_layer[l]
            hit = en & jnp.any(st.member[l] == m.src)
            mi = jnp.argmax(st.member[l] == m.src).astype(I32)
            st = dataclasses.replace(st, hb_seen=st.hb_seen.at[
                jnp.where(hit, l, lmax), mi].set(now, mode="drop"))

            # ---- LEADER_HB: authoritative membership ---------------
            l = jnp.clip(m.a, 0, lmax - 1)
            en = v & (m.kind == NICE_LEADER_HB) & is_ready
            inlist = jnp.any(m.nodes[:cmax] == node_idx)
            adopt = en & inlist
            row = jnp.where(adopt, l, lmax)
            now_row = jnp.zeros((cmax,), I64) + now
            st = dataclasses.replace(
                st,
                in_layer=st.in_layer.at[row].set(True, mode="drop"),
                leader=st.leader.at[row].set(m.src, mode="drop"),
                member=st.member.at[row].set(m.nodes[:cmax], mode="drop"),
                hb_seen=st.hb_seen.at[row].set(now_row, mode="drop"))
            # evicted by my own leader → drop the layer; layer-0 rejoins
            evict = en & ~inlist & st.in_layer[l] & (st.leader[l] == m.src)
            rejoin0 = evict & (l == 0)
            st = dataclasses.replace(
                st,
                in_layer=st.in_layer & ~(evict & (layer_idx >= l)),
                jn_stage=jnp.where(rejoin0, J_IDLE, st.jn_stage),
                jn_target=jnp.where(rejoin0, 0, st.jn_target),
                jn_deadline=jnp.where(rejoin0, now, st.jn_deadline))

            # ---- SPLIT: my cluster was bipartitioned ---------------
            l = jnp.clip(m.a, 0, lmax - 1)
            en = v & (m.kind == NICE_SPLIT) & is_ready
            adopt = en & jnp.any(m.nodes[:cmax] == node_idx)
            row = jnp.where(adopt, l, lmax)
            now_row = jnp.zeros((cmax,), I64) + now
            st = dataclasses.replace(
                st,
                leader=st.leader.at[row].set(m.b, mode="drop"),
                member=st.member.at[row].set(m.nodes[:cmax], mode="drop"),
                hb_seen=st.hb_seen.at[row].set(now_row, mode="drop"))
            # the new leader joins the upper anchor's cluster at l+1
            promo = adopt & (m.b == node_idx) & (m.c != NO_NODE) & (
                m.c != node_idx) & (l + 1 < lmax)
            ob.send(promo, now, jnp.maximum(m.c, 0), NICE_JOIN,
                    a=jnp.minimum(l + 1, lmax - 1), size_b=16)

            # ---- MERGE: absorb a dissolving sibling cluster --------
            l = jnp.clip(m.a, 0, lmax - 1)
            en = (v & (m.kind == NICE_MERGE) & is_ready & st.in_layer[l] &
                  (st.leader[l] == node_idx))
            mem = st.member[l]
            for ci in range(cmax):
                nd = m.nodes[ci]
                put = (en & (nd != NO_NODE) & ~jnp.any(mem == nd) &
                       jnp.any(mem == NO_NODE))
                slot = jnp.argmax(mem == NO_NODE).astype(I32)
                mem = mem.at[jnp.where(put, slot, cmax)].set(
                    nd, mode="drop")
            row = jnp.where(en, l, lmax)
            now_row = jnp.zeros((cmax,), I64) + now
            st = dataclasses.replace(
                st,
                member=st.member.at[row].set(mem, mode="drop"),
                hb_seen=st.hb_seen.at[row].set(now_row, mode="drop"))
            c_merges += en.astype(I32)

            # ---- MCAST: deliver once, queue the re-forward ---------
            en = v & (m.kind == NICE_MCAST) & is_ready
            h = (m.c.astype(I64) << 32) | m.b.astype(I64)
            dup = jnp.any(st.seen == h)
            fresh = en & ~dup
            c_recv += fresh.astype(I32)
            c_dup += (en & dup).astype(I32)
            ev.value("nice_hops", m.hops.astype(jnp.float32), fresh)
            st = self._seen_push(st, fresh, h)
            # queue ONE re-forward per tick (extra distinct arrivals in
            # the same 10-20ms window are counted, not re-forwarded —
            # publish periods are seconds apart so collisions are rare)
            c_fwdrop += (fresh & (st.fw_h != 0)).astype(I32)
            take = fresh & (st.fw_h == 0)
            st = dataclasses.replace(
                st,
                fw_h=jnp.where(take, h, st.fw_h),
                fw_src=jnp.where(take, m.src, st.fw_src),
                fw_origin=jnp.where(take, m.c, st.fw_origin),
                fw_seq=jnp.where(take, m.b, st.fw_seq),
                fw_layer=jnp.where(take, m.a, st.fw_layer),
                fw_hops=jnp.where(take, m.hops + 1, st.fw_hops))

        # ========================================= timers ==============
        rp = ctx.glob if ctx.glob is not None else NO_NODE
        is_ready = st.state == READY

        # ---- join / rejoin descent driver -----------------------------
        want = (st.state == JOINING) | (
            is_ready & ((st.jn_stage != J_IDLE) |
                        (st.jn_deadline < T_INF)))
        due = want & (st.jn_deadline < t_end)
        now_j = jnp.maximum(st.jn_deadline, t0)
        alone = due & ((rp == NO_NODE) | (rp == node_idx)) & (
            st.state == JOINING)
        st = self._become_root(st, alone, now_j, node_idx)
        c_joins += alone.astype(I32)

        # probe-round evaluation: deadline passed while PROBING
        eval_p = due & (st.jn_stage == J_PROBE) & st.jn_sent
        got = jnp.any(st.jn_rtt < T_INF)
        best_node = st.jn_cands[jnp.argmin(st.jn_rtt)]
        go_down = eval_p & got & (best_node != NO_NODE)
        nl = jnp.maximum(st.jn_layer - 1, st.jn_target)
        ob.send(go_down, now_j, jnp.maximum(best_node, 0), NICE_QUERY,
                a=nl, size_b=16)
        # a deadline expiring in QUERY or JOIN means the counterpart
        # never answered (dead leader, rejected join) — fall back to
        # IDLE so the restart below re-enters through the RP this same
        # tick (the reference's query timeout, handleTimerEvent
        # queryTimer → BasicJoinLayer retry)
        stuck = due & ~alone & ((st.jn_stage == J_QUERY) |
                                (st.jn_stage == J_JOIN))
        st = dataclasses.replace(
            st,
            jn_stage=jnp.where(go_down, J_QUERY,
                               jnp.where((eval_p & ~got) | stuck, J_IDLE,
                                         st.jn_stage)),
            jn_deadline=jnp.where(
                due & ~alone,
                now_j + jnp.int64(int(p.query_interval * NS)),
                st.jn_deadline))

        # (re)start of the descent: IDLE but wanting a layer → query RP
        lost0 = ~st.in_layer[0]
        restart = (due & ~alone & (st.jn_stage == J_IDLE) &
                   ((st.state == JOINING) | lost0 | (st.jn_target > 0)))
        ob.send(restart & (rp != NO_NODE), now_j, jnp.maximum(rp, 0),
                NICE_QUERY, a=jnp.int32(-1), size_b=16)
        st = dataclasses.replace(
            st, jn_stage=jnp.where(restart, J_QUERY, st.jn_stage))

        # fresh probe round: fire the probes (out of the inbox loop so
        # the CMAX-wide fan-out is traced once per tick, not per slot)
        fire_p = (st.jn_stage == J_PROBE) & ~st.jn_sent & (
            st.state != DEAD)
        for ci in range(cmax):
            nd = st.jn_cands[ci]
            ob.send(fire_p & (nd != NO_NODE) & (nd != node_idx), t0,
                    jnp.maximum(nd, 0), NICE_PROBE, stamp=t0, size_b=8)
        st = dataclasses.replace(
            st,
            jn_sent=jnp.where(fire_p, True, st.jn_sent),
            jn_deadline=jnp.where(
                fire_p, t0 + jnp.int64(int(p.probe_wait * NS)),
                st.jn_deadline))

        # ---- heartbeats ----------------------------------------------
        is_ready = st.state == READY
        en_hb = is_ready & (st.t_hb < t_end)
        now_h = jnp.maximum(st.t_hb, t0)
        for l in range(lmax):
            lead = st.in_layer[l] & (st.leader[l] == node_idx)
            memb = st.in_layer[l] & ~lead
            for ci in range(cmax):
                nd = st.member[l, ci]
                okd = (nd != NO_NODE) & (nd != node_idx)
                ob.send(en_hb & lead & okd, now_h, jnp.maximum(nd, 0),
                        NICE_LEADER_HB, a=jnp.int32(l),
                        nodes=st.member[l], size_b=list_b)
                ob.send(en_hb & memb & okd, now_h, jnp.maximum(nd, 0),
                        NICE_HB, a=jnp.int32(l), size_b=16)
        st = dataclasses.replace(
            st, t_hb=jnp.where(en_hb, now_h + hb_ns, st.t_hb))

        # ---- maintenance: evict / split / merge ----------------------
        en_mt = is_ready & (st.t_maint < t_end)
        now_m = jnp.maximum(st.t_maint, t0)
        timeout = jnp.int64(int(p.peer_timeout_hbs * p.hb_interval * NS))
        for l in range(lmax):
            act = en_mt & st.in_layer[l]
            lead = act & (st.leader[l] == node_idx)
            mem = st.member[l]
            valid = mem != NO_NODE
            stale = (valid & (mem != node_idx) &
                     (now_m - st.hb_seen[l] > timeout))
            # leader loses members → clear their slots
            c_evicts += jnp.sum(stale & lead, dtype=I32)
            row = jnp.where(lead, l, lmax)
            st = dataclasses.replace(st, member=st.member.at[row].set(
                jnp.where(stale, NO_NODE, mem), mode="drop"))
            # member loses its leader → rejoin this layer through RP
            lhit = jnp.any(mem == st.leader[l])
            li = jnp.argmax(mem == st.leader[l]).astype(I32)
            lost = (act & ~lead & lhit &
                    (now_m - st.hb_seen[l, li] > timeout))
            st = dataclasses.replace(
                st,
                in_layer=st.in_layer.at[
                    jnp.where(lost, l, lmax)].set(False, mode="drop"),
                jn_stage=jnp.where(lost, J_IDLE, st.jn_stage),
                jn_target=jnp.where(lost, l, st.jn_target),
                jn_deadline=jnp.where(lost, now_m, st.jn_deadline))

            # ---- split (> 3k-1 members; ClusterSplit Nice.cc:2621) ----
            mem = st.member[l]
            size = jnp.sum(mem != NO_NODE, dtype=I32)
            do_split = lead & (size > 3 * p.k - 1)
            c_splits += do_split.astype(I32)
            others = jnp.sort(jnp.where(  # analysis: allow(sort-call)
                (mem == NO_NODE) | (mem == node_idx), BIG, mem))
            others = jnp.where(others == BIG, NO_NODE, others)
            n_oth = jnp.sum(others != NO_NODE, dtype=I32)
            keep = size // 2 - 1               # others staying with me
            pos = jnp.arange(cmax, dtype=I32)
            half1 = jnp.where(pos == 0, node_idx,
                              jnp.where(pos - 1 < keep,
                                        jnp.take(others, jnp.clip(
                                            pos - 1, 0, cmax - 1)),
                                        NO_NODE))
            h2 = jnp.take(others, jnp.clip(pos + keep, 0, cmax - 1))
            half2 = jnp.where(pos < n_oth - keep, h2, NO_NODE)
            new_leader = half2[0]
            lup = min(l + 1, lmax - 1)
            has_up = st.in_layer[lup] if l + 1 < lmax else jnp.bool_(False)
            anchor = jnp.where(has_up, st.leader[lup], node_idx)
            for ci in range(cmax):
                nd = half2[ci]
                ob.send(do_split & (nd != NO_NODE), now_m,
                        jnp.maximum(nd, 0), NICE_SPLIT, a=jnp.int32(l),
                        b=new_leader, c=anchor, nodes=half2,
                        size_b=list_b)
            row = jnp.where(do_split, l, lmax)
            st = dataclasses.replace(
                st, member=st.member.at[row].set(half1, mode="drop"))
            # I was the top leader: a fresh upper cluster forms around me
            mkup = do_split & ~has_up & (l + 1 < lmax)
            memup = jnp.full((cmax,), NO_NODE, I32).at[0].set(node_idx)
            rowu = jnp.where(mkup, lup, lmax)
            st = dataclasses.replace(
                st,
                in_layer=st.in_layer.at[rowu].set(True, mode="drop"),
                leader=st.leader.at[rowu].set(node_idx, mode="drop"),
                member=st.member.at[rowu].set(memup, mode="drop"),
                hb_seen=st.hb_seen.at[rowu].set(
                    jnp.zeros((cmax,), I64) + now_m, mode="drop"))

            # ---- merge (< k members; ClusterMerge Nice.cc:2866) ----
            mem = st.member[l]
            size = jnp.sum(mem != NO_NODE, dtype=I32)
            up_mem = st.member[lup]
            peer_ok = (up_mem != NO_NODE) & (up_mem != node_idx)
            peer = up_mem[jnp.argmax(peer_ok)]
            do_merge = (lead & (size < p.k) & (l + 1 < lmax) &
                        st.in_layer[lup] & jnp.any(peer_ok))
            ob.send(do_merge, now_m, jnp.maximum(peer, 0), NICE_MERGE,
                    a=jnp.int32(l), nodes=mem, size_b=list_b)
            # demote: the absorbing peer owns the merged cluster; we
            # stay a plain member of layer l and leave the layers above
            row = jnp.where(do_merge, l, lmax)
            st = dataclasses.replace(
                st,
                leader=st.leader.at[row].set(peer, mode="drop"),
                in_layer=st.in_layer & ~(do_merge & (layer_idx > l)))
        st = dataclasses.replace(
            st, t_maint=jnp.where(
                en_mt, now_m + jnp.int64(int(p.maint_interval * NS)),
                st.t_maint))

        # ---- ALM workload: publish into all own clusters --------------
        is_ready = st.state == READY
        fw = st.fw_h != 0
        pub_due = is_ready & (st.t_pub < t_end)
        en_pub = pub_due & ctx.measuring & ~fw
        now_pb = jnp.maximum(st.t_pub, t0)
        seq = st.seq + en_pub.astype(I32)
        h = (node_idx.astype(I64) << 32) | seq.astype(I64)
        c_pub += en_pub.astype(I32)
        st = self._seen_push(st, en_pub, h)
        st = dataclasses.replace(
            st, seq=seq,
            t_pub=jnp.where(
                pub_due, now_pb + jnp.int64(int(p.pub_interval * NS)),
                st.t_pub))
        nlayers = jnp.sum(st.in_layer, dtype=I32)
        ev.value("nice_layers", nlayers.astype(jnp.float32), en_pub)

        # ---- unified dissemination fan-out ----------------------------
        # one fan-out per tick: either my own publish (arrival layer -1)
        # or the queued re-forward from the inbox sweep
        go = fw | en_pub
        g_origin = jnp.where(fw, st.fw_origin, node_idx)
        g_seq = jnp.where(fw, st.fw_seq, seq)
        g_src = jnp.where(fw, st.fw_src, node_idx)
        g_layer = jnp.where(fw, st.fw_layer, -1)
        g_hops = jnp.where(fw, st.fw_hops, 0)
        now_f = jnp.where(fw, t0, now_pb)
        for l in range(lmax):
            into = go & st.in_layer[l] & (l != g_layer)
            for ci in range(cmax):
                nd = st.member[l, ci]
                ob.send(into & (nd != NO_NODE) & (nd != node_idx) &
                        (nd != g_src), now_f, jnp.maximum(nd, 0),
                        NICE_MCAST, a=jnp.int32(l), b=g_seq, c=g_origin,
                        hops=g_hops, size_b=60)
        st = dataclasses.replace(
            st,
            fw_h=jnp.where(fw, 0, st.fw_h),
            fw_src=jnp.where(fw, NO_NODE, st.fw_src))

        events = {"c:nice_joins": c_joins, "c:nice_pub": c_pub,
                  "c:nice_recv": c_recv, "c:nice_dup": c_dup,
                  "c:nice_splits": c_splits, "c:nice_merges": c_merges,
                  "c:nice_evicts": c_evicts, "c:nice_fwd_drop": c_fwdrop}
        ev.finish(events, {})
        return st, ob, events
