"""Pastry / Bamboo prefix-routing DHT as vectorized per-node logic.

TPU-native rebuild of the reference BasePastry/Pastry/Bamboo family
(src/overlay/pastry/BasePastry.{h,cc}, Pastry.{h,cc}, bamboo/Bamboo.{h,cc};
defaults simulations/default.ini:226-267: bitsPerDigit=4,
numberOfLeaves=16 (Bamboo 8)).  State is structure-of-arrays:

  * leaf set as two ring-sorted halves [N, L/2] (clockwise successors +
    counter-clockwise predecessors — reference PastryLeafSet keeps the
    bigger/smaller halves);
  * prefix routing table [N, ROWS, 2^b]: row r column c holds a node
    sharing r digits with our key whose digit r is c
    (PastryRoutingTable); rows are capped (ROWS*b prefix bits is far
    beyond the populated region for any realistic N — deeper keys are
    the leafset's job);
  * findNode (BasePastry.cc:1100): leafset if the key is within leafset
    range (numerically closest leaf wins), else the routing-table entry
    for [sharedPrefixDigits, next digit], else the numerically-closest
    known node with at-least-equal prefix (fallback);
  * isSiblingFor: numSiblings closest of leafset ∪ self by Pastry's
    plain numeric metric;
  * join: iterative lookup of the own key, then a state exchange with
    the responsible node (the reference collects PastryStateMessages
    from every hop of the routed join, Pastry.cc:1071; here the
    leafset arrives from the responsible node and the routing table
    fills from exchanges + observed traffic — Bamboo's push-pull
    convergence, Bamboo.cc localTuning/leafsetMaintenance);
  * maintenance (Bamboo-style, used for both variants): periodic
    leafset push-pull with a random leaf (`leafsetMaintenanceInterval`),
    periodic random-key lookup filling routing-table rows
    (`globalTuningInterval`); Pastry's reactive leafset repair
    (handleFailedNode → state request to the farthest leaf) rides the
    same exchange message;
  * proximity neighbor selection (PNS, BasePastry.cc:439-570
    pingNodes/proximity compare): every state exchange carries an RTT
    stamp; the responder's measured RTT gates routing-table adoption —
    a measured-closer candidate replaces an occupied slot (rt_rtt
    table), unmeasured candidates only fill empty slots.  The
    neighborhood set (purely a PNS seed cache in the reference) is
    subsumed by the same RTT table.

Routing mode defaults to SEMI_RECURSIVE with per-hop ACKs — the
reference's Pastry configuration (default.ini:245-246 routeMsgAcks=true,
routingType="semi-recursive"): application payloads hop node-to-node via
common/route.py (findNode → loop-detect → forward, NextHop ACK, reroute
on hop failure), while join/maintenance lookups stay iterative.
``routing_mode="iterative"`` restores lookup-then-direct-hop routing.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps.kbrtest import KbrTestApp
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.common import neighborcache as nc_mod
from oversim_tpu.common import route as rt_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
UMAX = jnp.uint32(0xFFFFFFFF)
RTT_INF = jnp.int32(2**30)

DEAD, JOINING, READY = 0, 1, 2

P_JOIN, P_TUNE, P_APP = 1, 2, 3


@dataclasses.dataclass(frozen=True)
class PastryParams:
    """default.ini:226-267."""

    bits_per_digit: int = 4       # bitsPerDigit
    num_leaves: int = 16          # numberOfLeaves (Bamboo: 8)
    rows: int = 16                # routing-table row cap (see module doc)
    join_delay: float = 10.0
    leafset_interval: float = 10.0   # Bamboo leafsetMaintenanceInterval
    tuning_interval: float = 30.0    # Bamboo globalTuningInterval
    rpc_timeout: float = 1.5
    # reference default.ini:245-246: semi-recursive with per-hop ACKs
    routing_mode: str = "semi-recursive"   # or "iterative"
    route_acks: bool = True       # routeMsgAcks
    rec_redundant: int = 4        # recNumRedundantNodes (default.ini:386: 3)
    adaptive_timeouts: bool = False  # optimizeTimeouts (BaseRpc.cc:197-
                                  # 205): iterative-lookup RPC timeouts
                                  # from the NeighborCache estimator
                                  # (getNodeTimeout, NeighborCache.cc:802)

    @property
    def cols(self) -> int:
        return 1 << self.bits_per_digit

    @property
    def half(self) -> int:
        return self.num_leaves // 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PastryState:
    state: jnp.ndarray      # [N] i32
    leaf_cw: jnp.ndarray    # [N, L/2] i32 clockwise (successor side)
    leaf_ccw: jnp.ndarray   # [N, L/2] i32 counter-clockwise
    rt: jnp.ndarray         # [N, ROWS, COLS] i32
    rt_rtt: jnp.ndarray     # [N, ROWS, COLS] i32 RTT ms of each entry
                            # (PNS state, BasePastry.cc:439-570 pingNodes)
    t_join: jnp.ndarray     # [N] i64
    t_ls: jnp.ndarray       # [N] i64 leafset maintenance
    t_gt: jnp.ndarray       # [N] i64 global tuning
    lk: lk_mod.LookupState
    rr: rt_mod.RouteState   # [N, Q, ...] pending-ACK recursive routes
    nc: object              # nc_mod.NcState — RTT cache (adaptive timeouts)
    app: object
    app_glob: object


class PastryLogic:
    """Engine logic interface; Bamboo = PastryLogic(bamboo defaults)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: PastryParams = PastryParams(),
                 lcfg: lk_mod.LookupConfig | None = None,
                 app=None):
        self.key_spec = spec
        self.p = params
        self.lcfg = lcfg or lk_mod.LookupConfig()
        self.rcfg = rt_mod.RouteConfig(route_acks=params.route_acks)
        self.app = app or KbrTestApp()
        if getattr(self.app, "rcfg", None) is None:
            # Pastry routes semi-recursively by default: the app must
            # know (for reply transport + the deliver dedup ring,
            # apps/kbrtest.py KbrTestApp.buf)
            self.app.rcfg = self.rcfg
        # Pastry responsibility = numeric closeness on the ring
        # (BasePastry::distance, KeyDiffMetric)
        if getattr(self.app, "dist_fn", "no") is None:
            self.app.dist_fn = (
                lambda nk, rk: K.bidir_ring_distance(nk, rk, spec))

    # -- engine interface ---------------------------------------------------

    def split(self, st: PastryState):
        return dataclasses.replace(st, app_glob=None), st.app_glob

    def merge(self, node_part: PastryState, glob):
        return dataclasses.replace(node_part, app_glob=glob)

    def post_step(self, ctx, st: PastryState, events):
        app, glob = self.app.post_step(ctx, st.app, st.app_glob, events)
        return dataclasses.replace(st, app=app, app_glob=glob)

    def stat_spec(self) -> stats_mod.StatSpec:
        app = self.app.stat_spec()
        return stats_mod.StatSpec(
            scalars=tuple(app["scalars"]) + ("lookup_hops",),
            hists=tuple(app["hists"]),
            counters=tuple(app["counters"]) + (
                "pastry_joins", "lookup_success", "lookup_failed",
                "route_dropped"),
        )

    def init(self, rng, n: int) -> PastryState:
        p = self.p
        return PastryState(
            state=jnp.zeros((n,), I32),
            leaf_cw=jnp.full((n, p.half), NO_NODE, I32),
            leaf_ccw=jnp.full((n, p.half), NO_NODE, I32),
            rt=jnp.full((n, p.rows, p.cols), NO_NODE, I32),
            rt_rtt=jnp.full((n, p.rows, p.cols), RTT_INF, I32),
            t_join=jnp.full((n,), T_INF, I64),
            t_ls=jnp.full((n,), T_INF, I64),
            t_gt=jnp.full((n,), T_INF, I64),
            lk=jax.vmap(lambda _: lk_mod.init(self.lcfg, self.key_spec.lanes))(
                jnp.arange(n)),
            rr=jax.vmap(lambda _: rt_mod.init(
                self.rcfg, self.key_spec.lanes, 16))(jnp.arange(n)),
            nc=nc_mod.init(n, nc_mod.NcParams(
                capacity=16 if self.p.adaptive_timeouts else 1)),
            app=self.app.init(n),
            app_glob=self.app.glob_init(rng),
        )

    def reset(self, st: PastryState, clear, join, t_now, rng):
        n = st.state.shape[0]
        glob = st.app_glob
        st = dataclasses.replace(st, app_glob=None)
        fresh = dataclasses.replace(self.init(rng, n), app_glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, app_glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: PastryState):
        return st.state == READY

    def next_event(self, st: PastryState):
        joining = st.state == JOINING
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        for timer in (st.t_ls, st.t_gt):
            t = jnp.minimum(t, jnp.where(ready, timer, T_INF))
        t = jnp.minimum(t, jnp.where(ready, self.app.next_event(st.app),
                                     T_INF))
        t = jnp.minimum(t, jax.vmap(lk_mod.next_event)(st.lk))
        t = jnp.minimum(t, jax.vmap(rt_mod.next_event)(st.rr))
        return t

    # -- internals (per-node slice) ------------------------------------------

    def _half_sorted(self, ctx, me_key, node_idx, cands, clockwise: bool):
        """L/2 ring-closest candidates on one side, sorted by distance."""
        h = self.p.half
        bad = (cands == NO_NODE) | (cands == node_idx) | K.dup_mask(cands)
        ck = ctx.keys[jnp.maximum(cands, 0)]
        me_b = jnp.broadcast_to(me_key, ck.shape)
        d = K.sub(ck, me_b, self.key_spec) if clockwise \
            else K.sub(me_b, ck, self.key_spec)
        d = jnp.where(bad[:, None], UMAX, d)
        (c_s, bad_s) = K.sort_by_distance(d, (cands, bad.astype(I32)),
                                          approx=True)[1]
        return jnp.where(bad_s[:h] != 0, NO_NODE, c_s[:h])

    def _leaf_merge(self, ctx, st, me_key, node_idx, cands, en):
        """Merge candidate slots into both leafset halves
        (PastryLeafSet::mergeNode)."""
        cands = jnp.where(en, cands, NO_NODE)
        all_cw = jnp.concatenate([st.leaf_cw, cands])
        all_ccw = jnp.concatenate([st.leaf_ccw, cands])
        return dataclasses.replace(
            st,
            leaf_cw=self._half_sorted(ctx, me_key, node_idx, all_cw, True),
            leaf_ccw=self._half_sorted(ctx, me_key, node_idx, all_ccw,
                                       False))

    def _rt_add(self, ctx, st, me_key, node_idx, cands, en, rtt=None):
        """Insert candidates into routing-table slots with proximity
        neighbor selection (PastryRoutingTable::mergeNode + the PNS
        ping-before-adopt comparison, BasePastry.cc:439-570: a measured
        closer candidate replaces an occupied slot; unmeasured
        candidates only fill empty slots)."""
        p = self.p
        rt, rt_rtt = st.rt, st.rt_rtt
        for i in range(cands.shape[0]):
            c = jnp.where(en[i] & (cands[i] != node_idx), cands[i], NO_NODE)
            c_rtt = RTT_INF if rtt is None else rtt[i]
            ck = ctx.keys[jnp.maximum(c, 0)]
            row = jnp.minimum(
                K.shared_prefix_digits(me_key, ck, p.bits_per_digit,
                                       self.key_spec), p.rows - 1)
            col = K.digit(ck, row, p.bits_per_digit, self.key_spec)
            empty = rt[row, col] == NO_NODE
            same = rt[row, col] == c
            closer = c_rtt < rt_rtt[row, col]
            do = (c != NO_NODE) & (empty | closer | same)
            r = jnp.where(do, row, p.rows)
            rt = rt.at[r, col].set(c, mode="drop")
            rt_rtt = rt_rtt.at[r, col].set(
                jnp.where(same & ~closer, rt_rtt[row, col],
                          jnp.asarray(c_rtt, I32)), mode="drop")
        return dataclasses.replace(st, rt=rt, rt_rtt=rt_rtt)

    def _learn(self, ctx, st, me_key, node_idx, cands, en, rtt=None):
        st = self._leaf_merge(ctx, st, me_key, node_idx, cands, en)
        return self._rt_add(ctx, st, me_key, node_idx, cands, en, rtt)

    def _leafset_nodes(self, st, node_idx):
        """Own state payload: self + both halves (PastryStateMessage)."""
        return jnp.concatenate([node_idx[None], st.leaf_cw, st.leaf_ccw])

    def _find_node(self, ctx, st, me_key, node_idx, key, rmax):
        """BasePastry::findNode (BasePastry.cc:1100).

        All closeness uses the reference's keyDist = bidirectional ring
        distance (PastryStateObject::keyDist, PastryStateObject.cc:107).
        Returns ([rmax] result slots, is_sibling bool).
        """
        p, spec = self.p, self.key_spec

        def kdist(slots, target):
            ck = ctx.keys[jnp.maximum(slots, 0)]
            d = K.bidir_ring_distance(ck, jnp.broadcast_to(target, ck.shape),
                                      spec)
            return jnp.where((slots == NO_NODE)[:, None], UMAX, d)

        ready = st.state == READY
        me_d = K.bidir_ring_distance(me_key, key, spec)

        # isClosestNode (PastryLeafSet.cc:136): neither the immediate
        # clockwise nor counter-clockwise neighbor is closer than us
        big, small = st.leaf_cw[0], st.leaf_ccw[0]
        no_nbrs = (big == NO_NODE) & (small == NO_NODE)
        big_closer = (big != NO_NODE) & K.lt(kdist(big[None], key)[0], me_d)
        small_closer = (small != NO_NODE) & K.lt(kdist(small[None], key)[0],
                                                 me_d)
        is_sib = ready & (K.eq(key, me_key) | no_nbrs
                          | (~big_closer & ~small_closer))

        # getDestinationNode (PastryLeafSet.cc:106): key within the
        # leafset span [farthest-ccw, farthest-cw] → closest leaf
        def farthest(half):
            n_valid = jnp.sum((half != NO_NODE).astype(I32))
            return jnp.where(n_valid > 0, half[jnp.maximum(n_valid - 1, 0)],
                             NO_NODE)

        cw_far, ccw_far = farthest(st.leaf_cw), farthest(st.leaf_ccw)
        span_ok = (cw_far != NO_NODE) & (ccw_far != NO_NODE)
        in_span = span_ok & K.is_between_lr(
            key, ctx.keys[jnp.maximum(ccw_far, 0)],
            ctx.keys[jnp.maximum(cw_far, 0)], spec)
        leafs = self._leafset_nodes(st, node_idx)
        d_leafs = kdist(leafs, key)
        (leafs_s,) = K.sort_by_distance(d_leafs, (leafs,), approx=True)[1]
        leaf_dest = leafs_s[0]

        # routing table hop (PastryRoutingTable::lookupNextHop)
        row = jnp.minimum(
            K.shared_prefix_digits(me_key, key, p.bits_per_digit, spec),
            p.rows - 1)
        col = K.digit(key, row, p.bits_per_digit, spec)
        rt_hop = st.rt[row, col]
        rt_ok = rt_hop != NO_NODE

        # 'rare case' fallback (BasePastry.cc:1132-1165 findCloserNode):
        # any known node with >= shared prefix strictly closer by keyDist
        known = jnp.concatenate([leafs, st.rt.reshape(-1)])
        kk = ctx.keys[jnp.maximum(known, 0)]
        key_b = jnp.broadcast_to(key, kk.shape)
        dk = kdist(known, key)
        closer = K.lt(dk, jnp.broadcast_to(me_d, dk.shape))
        pfx = K.shared_prefix_digits(me_key, key, p.bits_per_digit, spec)
        kpfx = K.shared_prefix_digits(kk, key_b, p.bits_per_digit, spec)
        ok = (known != NO_NODE) & closer & (kpfx >= pfx)
        df = jnp.where(ok[:, None], dk, UMAX)
        (fb_s,) = K.sort_by_distance(df, (known,), approx=True)[1]
        fallback = jnp.where(jnp.any(ok), fb_s[0], NO_NODE)

        # result set: sibling case → closest leafs (replica set); else hop
        nxt = jnp.where(in_span & (leaf_dest != node_idx), leaf_dest,
                        jnp.where(rt_ok, rt_hop, fallback))
        res = jnp.full((rmax,), NO_NODE, I32)
        res_sib = res.at[:leafs_s.shape[0]].set(leafs_s[:rmax])
        res = jnp.where(is_sib, res_sib, res.at[0].set(nxt))
        res = jnp.where(ready, res, jnp.full((rmax,), NO_NODE, I32))

        # redundant next-hop candidates for recursive forwarding, in
        # preference order (recNumRedundantNodes, default.ini:386): the
        # primary hop (self when responsible), then the keyDist-sorted
        # closer-known fallbacks for loop avoidance/reroute
        cands = jnp.concatenate(
            [jnp.where(is_sib, node_idx, nxt)[None],
             fb_s[:max(p.rec_redundant - 1, 0)]])
        cands = jnp.where(ready, cands, NO_NODE)
        return res, is_sib, cands

    def _handle_failed(self, ctx, st, me_key, node_idx, failed, ob, now):
        """BasePastry::handleFailedNode + Pastry leafset repair: drop the
        failed nodes everywhere; if a leafset half lost a member, request
        state from the farthest remaining leaf."""
        any_failed = jnp.any(failed != NO_NODE)

        def hit(x):
            return (x[..., None] == failed).any(-1) & (x != NO_NODE)

        lost_leaf = jnp.any(hit(st.leaf_cw)) | jnp.any(hit(st.leaf_ccw))
        leaf_cw = jnp.where(hit(st.leaf_cw), NO_NODE, st.leaf_cw)
        leaf_ccw = jnp.where(hit(st.leaf_ccw), NO_NODE, st.leaf_ccw)
        # re-sort each half so survivors from the other half can slide in
        st2 = self._leaf_merge(
            ctx, dataclasses.replace(st, leaf_cw=leaf_cw, leaf_ccw=leaf_ccw),
            me_key, node_idx,
            jnp.concatenate([leaf_cw, leaf_ccw]),
            jnp.ones((2 * self.p.half,), bool))
        st = select_tree(any_failed, st2, st)
        st = dataclasses.replace(
            st, rt=jnp.where(hit(st.rt), NO_NODE, st.rt),
            rt_rtt=jnp.where(hit(st.rt), RTT_INF, st.rt_rtt))
        # repair: ask the farthest remaining leaf for its state
        repair_tgt = jnp.where(st.leaf_cw[-1] != NO_NODE, st.leaf_cw[-1],
                               st.leaf_cw[0])
        fire = any_failed & lost_leaf & (repair_tgt != NO_NODE) & (
            st.state == READY)
        ob.send(fire, now, repair_tgt, wire.PASTRY_STATE_CALL,
                stamp=now, size_b=wire.BASE_CALL_B)
        return st

    def _become_ready(self, ctx, st, en, now, rng):
        p = self.p
        return dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            t_join=jnp.where(en, T_INF, st.t_join),
            t_ls=jnp.where(en, now, st.t_ls),
            t_gt=jnp.where(en, now + jnp.int64(
                int(p.tuning_interval * NS)), st.t_gt),
            app=self.app.on_ready(st.app, en, now, rng))

    # -- the per-node step ---------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, lcfg, spec = self.p, self.lcfg, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rngs = jax.random.split(rng, 6)
        t0 = ctx.t_start
        t_end = ctx.t_end

        def metric_fn(cand_slots, target):
            ck = ctx.keys[jnp.maximum(cand_slots, 0)]
            d = K.bidir_ring_distance(
                ck, jnp.broadcast_to(target, ck.shape), spec)
            return jnp.where((cand_slots == NO_NODE)[:, None], UMAX, d)

        def pad_nodes(vec):
            out = jnp.full((rmax,), NO_NODE, I32)
            return out.at[:min(vec.shape[0], rmax)].set(vec[:rmax])

        ev = app_base.AppEvents()
        joins_cnt = jnp.int32(0)
        anyfail_cnt = jnp.int32(0)
        lksucc_cnt = jnp.int32(0)
        routedrop_cnt = jnp.int32(0)
        old_leaf = jnp.concatenate([st.leaf_cw, st.leaf_ccw])
        # update() delta base (the leafset is Pastry's sibling set)

        # ------------------------------------------------------- inbox -----
        if p.adaptive_timeouts:
            # FindNode RTT samples feed the NeighborCache estimator
            # before the per-slot handlers clear the pendings
            # (NeighborCache::updateNode on every RPC response)
            en_rtt = msgs.valid & (msgs.kind == wire.FINDNODE_RES)
            rtt_src, rtt_s, rtt_ok = lk_mod.response_rtts(
                st.lk, dataclasses.replace(msgs, valid=en_rtt))
            st = dataclasses.replace(st, nc=nc_mod.feed_response_rtts(
                st.nc, rtt_src, rtt_s, msgs.t_deliver, rtt_ok))
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid

            # learn every READY message source (observed-traffic table
            # fill, Bamboo's passive learning).  Joining nodes must NOT
            # enter leafsets: the reference only merges overlay members
            # (PastryStateMessage senders); adopting a joiner would route
            # its own-key join lookup straight back at it.
            src_ready = ctx.ready[jnp.maximum(m.src, 0)]
            st = select_tree(
                v & src_ready,
                self._learn(ctx, st, me_key, node_idx, m.src[None],
                            jnp.ones((1,), bool)), st)

            # local findNode on this slot's key — shared by the FindNode
            # RPC server, the recursive forwarding pre-pass, and the app
            # delivery sibling check below
            res, sib, cands = self._find_node(ctx, st, me_key, node_idx,
                                              m.key, rmax)

            # per-hop ACK bookkeeping (NextHopResponse)
            st = dataclasses.replace(st, rr=rt_mod.on_ack(
                st.rr, dataclasses.replace(
                    m, valid=v & (m.kind == wire.KBR_ROUTE_ACK))))

            # recursive route pre-pass (sendToKey SEMI_RECURSIVE hop,
            # BaseOverlay.cc:1441-1581): ACK the last hop, then either
            # decapsulate (responsible) or forward to the first candidate
            # surviving loop detection.  visitedHops ride m.nodes; the
            # originator is visited[0].
            en_rt = v & (m.kind == wire.KBR_ROUTE) & (st.state == READY)
            ob.send(en_rt & (m.nonce > 0), now, m.src, wire.KBR_ROUTE_ACK,
                    nonce=m.nonce, size_b=wire.BASE_CALL_B)
            deliver = en_rt & sib
            nxt_rt, found_rt = rt_mod.pick_next_hop(
                cands, m.nodes, m.src, m.nodes[0], node_idx, sib)
            fwd = en_rt & ~sib & found_rt & (m.hops < self.rcfg.hop_max)
            if hasattr(self.app, "forward"):
                # Common API forward() veto (BaseApp.h:214)
                fwd = fwd & ~self.app.forward(st.app, m, ctx)
            vis_n = jnp.sum((m.nodes != NO_NODE).astype(I32))
            visited2 = m.nodes.at[jnp.minimum(vis_n, rmax - 1)].set(
                jnp.where(fwd, node_idx, m.nodes[jnp.minimum(
                    vis_n, rmax - 1)]))
            st = dataclasses.replace(st, rr=rt_mod.forward(
                st.rr, ob, fwd, now, nxt_rt, key=m.key, inner=m.d,
                a=m.a, b=m.b, c=m.c, hops=m.hops + 1, stamp=m.stamp,
                size_b=m.size_b - self.rcfg.overhead_b, visited=visited2,
                cfg=self.rcfg))
            routedrop_cnt += (en_rt & ~sib & ~fwd).astype(I32)
            # decapsulate at the responsible node: the payload kind takes
            # over and src becomes the originator, so the handlers below
            # (incl. FindNodeCall for recursive lookups and app kinds)
            # consume it as if it arrived directly
            m = dataclasses.replace(
                m,
                kind=jnp.where(deliver, m.d, m.kind),
                src=jnp.where(deliver, m.nodes[0], m.src),
                valid=v & (~en_rt | deliver))
            v = m.valid

            # FindNodeCall
            en = v & (m.kind == wire.FINDNODE_CALL)
            n_res = jnp.sum((res != NO_NODE).astype(I32))
            ob.send(en, now, m.src, wire.FINDNODE_RES, key=m.key,
                    a=m.a, b=m.b, c=sib.astype(I32), nodes=res,
                    size_b=wire.BASE_CALL_B + 1 + wire.NODEHANDLE_B * n_res)

            # FindNodeResponse → lookup engine + learn payload
            en = v & (m.kind == wire.FINDNODE_RES)
            st = dataclasses.replace(st, lk=lk_mod.on_response(
                st.lk, dataclasses.replace(m, valid=en), metric_fn, lcfg))
            learned = m.nodes[:lcfg.frontier]
            st = select_tree(
                en, self._learn(ctx, st, me_key, node_idx, learned,
                                learned != NO_NODE), st)

            # state exchange (leafset push-pull; PastryStateMessage)
            en = v & (m.kind == wire.PASTRY_STATE_CALL) & (
                st.state == READY)
            ob.send(en, now, m.src, wire.PASTRY_STATE_RES,
                    nodes=pad_nodes(self._leafset_nodes(st, node_idx)),
                    stamp=m.stamp, size_b=wire.BASE_CALL_B
                    + wire.NODEHANDLE_B * (p.num_leaves + 1))
            en = v & (m.kind == wire.PASTRY_STATE_RES)
            rtt_ms = jnp.clip((now - m.stamp) // 1_000_000, 0,
                              RTT_INF - 1).astype(I32)
            rtt_vec = jnp.full((rmax,), RTT_INF, I32).at[0].set(
                jnp.where(m.stamp > 0, rtt_ms, RTT_INF))
            st = select_tree(
                en, self._learn(ctx, st, me_key, node_idx,
                                m.nodes[:rmax], m.nodes[:rmax] != NO_NODE,
                                rtt=rtt_vec),
                st)
            # joining node: first state response completes the join
            got_state = en & (st.state == JOINING)
            joins_cnt += got_state.astype(I32)
            st = self._become_ready(ctx, st, got_state, now, rngs[0])

            # app-owned kinds (reuse the sibling flag computed for this
            # slot's FindNode handler — no app-kind handler above mutates
            # the tables it read)
            st = dataclasses.replace(st, app=self.app.on_msg(
                st.app, m, ctx, ob, ev, sib))

            # generic ping
            ob.send(v & (m.kind == wire.PING_CALL), now, m.src,
                    wire.PING_RES, a=m.a, size_b=wire.BASE_CALL_B)

        # ------------------------------------------------------- timers ----
        # join: lookup own key, then state request to the responsible node
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        no_join_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_JOIN))
        alone_start = en_j & (boot == NO_NODE)
        st = self._become_ready(ctx, st, alone_start, now_j, rngs[2])
        joins_cnt += alone_start.astype(I32)
        slot, have = lk_mod.free_slot(st.lk)
        start_join = en_j & (boot != NO_NODE) & no_join_lk & have
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(boot)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_join, slot, P_JOIN, 0, me_key, seed, now_j, lcfg))
        st = dataclasses.replace(st, t_join=jnp.where(
            en_j & ~alone_start,
            now_j + jnp.int64(int(p.join_delay * NS)), st.t_join))

        # leafset maintenance: push-pull with a random leaf (Bamboo
        # leafsetMaintenance)
        en_l = (st.state == READY) & (st.t_ls < t_end)
        now_l = jnp.maximum(st.t_ls, t0)
        leafs = jnp.concatenate([st.leaf_cw, st.leaf_ccw])
        n_leafs = jnp.sum((leafs != NO_NODE).astype(I32))
        pick = jax.random.randint(rngs[3], (), 0, jnp.maximum(n_leafs, 1),
                                  dtype=I32)
        order = jnp.argsort(jnp.where(leafs != NO_NODE, 0, 1))  # analysis: allow(sort-call)
        tgt = leafs[order[jnp.minimum(pick, leafs.shape[0] - 1)]]
        fire_l = en_l & (tgt != NO_NODE)
        ob.send(fire_l, now_l, tgt, wire.PASTRY_STATE_CALL,
                stamp=now_l, size_b=wire.BASE_CALL_B)
        st = dataclasses.replace(st, t_ls=jnp.where(
            en_l, now_l + jnp.int64(int(p.leafset_interval * NS)), st.t_ls))

        # global tuning: random-key lookup fills routing rows (Bamboo
        # globalTuning)
        en_g = (st.state == READY) & (st.t_gt < t_end)
        now_g = jnp.maximum(st.t_gt, t0)
        no_tune = ~jnp.any(st.lk.active & (st.lk.purpose == P_TUNE))
        target = K.random_keys(rngs[4], (), spec)
        seed_g, sib_g, _ = self._find_node(ctx, st, me_key, node_idx,
                                           target, rmax)
        slot, have = lk_mod.free_slot(st.lk)
        start_g = en_g & no_tune & have & ~sib_g & (seed_g[0] != NO_NODE)
        st = dataclasses.replace(
            st,
            lk=lk_mod.start(st.lk, start_g, slot, P_TUNE, 0, target,
                            seed_g[:lcfg.frontier], now_g, lcfg),
            t_gt=jnp.where(en_g, now_g + jnp.int64(
                int(p.tuning_interval * NS)), st.t_gt))

        # app timer
        # graceful-leave: hand app data to the clockwise leaf and stop
        # firing app tests during the grace window (apps/base.py on_leave)
        st = dataclasses.replace(st, app=app_base.leave_protocol(
            self.app, st.app, ctx, ob, ev, t0, node_idx, st.leaf_cw[0],
            st.state == READY))
        en_a = (st.state == READY) & (
            self.app.next_event(st.app) < t_end)
        now_a = jnp.maximum(self.app.next_event(st.app), t0)
        app, req = self.app.on_timer(st.app, en_a, ctx, now_a, rngs[5], ev, node_idx)
        st = dataclasses.replace(st, app=app)
        seed_a, sib_a, cands_a = self._find_node(ctx, st, me_key, node_idx,
                                                 req.key, rmax)
        local = req.want & sib_a
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=local, success=local, tag=req.tag,
                target=req.key,
                results=jnp.where(local, seed_a[:lcfg.frontier], NO_NODE),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        # Which app requests ride the recursive data path?  Only the
        # payloads the app DECLARES routable (route_policy — kbrtest's
        # one-way/RPC tests).  Everything else (DHT LookupCalls, the
        # kbr lookup test) needs a SIBLING-SET completion and goes
        # through the iterative lookup engine even in semi-recursive
        # mode, exactly like the reference (DHT.cc issues LookupCall
        # regardless of the overlay's data routingType).  Routing every
        # request as APP_ONEWAY data was the round-3 verify_pastry
        # golden's 1%-put-success bug.
        use_route = (self.p.routing_mode == "semi-recursive"
                     and hasattr(self.app, "route_policy"))
        if use_route:
            routable, inner_a, is_rpc = self.app.route_policy(req.tag)
            vis0 = jnp.full((rmax,), NO_NODE, I32).at[0].set(node_idx)
            nxt0, found0 = rt_mod.pick_next_hop(
                cands_a, jnp.full((rmax,), NO_NODE, I32), NO_NODE,
                node_idx, node_idx, sib_a)
            fire0 = req.want & ~sib_a & routable & found0
            st = dataclasses.replace(st, rr=rt_mod.forward(
                st.rr, ob, fire0, now_a, nxt0, key=req.key,
                inner=inner_a, a=req.tag, b=jnp.int32(0),
                c=ctx.measuring.astype(I32), hops=jnp.int32(1),
                stamp=now_a, size_b=jnp.int32(100), visited=vis0,
                cfg=self.rcfg))
            if hasattr(self.app, "on_route_fired"):
                st = dataclasses.replace(st, app=self.app.on_route_fired(
                    st.app, fire0 & is_rpc, now_a, req.tag))
            routedrop_cnt += (req.want & ~sib_a & routable
                              & ~found0).astype(I32)
        else:
            routable = jnp.bool_(False)
            fire0 = jnp.bool_(False)
        slot, have = lk_mod.free_slot(st.lk)
        start_app = (req.want & ~sib_a & ~routable & have
                     & (seed_a[0] != NO_NODE))
        # a routable request with NO next hop must fail its op too
        # (chord/kademlia: insta_fail = ~start_app & ~route_fire) — else
        # routed-RPC tests leak into a never-resolved state
        insta_fail = req.want & ~sib_a & ~start_app & ~fire0
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=insta_fail, success=jnp.bool_(False), tag=req.tag,
                target=req.key,
                results=jnp.full((lcfg.frontier,), NO_NODE, I32),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_app, slot, P_APP, req.tag, req.key,
            seed_a[:lcfg.frontier], now_a, lcfg))

        # ------------------------------------------------ lookup timeouts --
        new_lk, failed_nodes, _ = lk_mod.on_timeouts(st.lk, t_end, t0, lcfg)
        st = dataclasses.replace(st, lk=new_lk)
        # route-hop ACK timeouts: unresponsive next hops are failures too
        new_rr, rt_failed, rt_retry = rt_mod.on_timeouts(st.rr, t_end,
                                                         self.rcfg)
        st = dataclasses.replace(st, rr=new_rr)
        st = self._handle_failed(
            ctx, st, me_key, node_idx,
            jnp.concatenate([failed_nodes, rt_failed]), ob, t0)

        # reroute parked messages around the failed hop (the hop was just
        # dropped from all tables by _handle_failed, so a fresh findNode
        # yields the alternative; internalHandleRpcTimeout :1697-1729)
        for qi in range(self.rcfg.slots):
            en_q = rt_retry[qi]
            _, sib_q, cands_q = self._find_node(
                ctx, st, me_key, node_idx, st.rr.key[qi], rmax)
            nxt_q, found_q = rt_mod.pick_next_hop(
                cands_q, st.rr.visited[qi], NO_NODE,
                st.rr.visited[qi, 0], node_idx, sib_q)
            # became responsible ourselves meanwhile → self-forward
            # delivers (decap) next tick
            st = dataclasses.replace(st, rr=rt_mod.reforward(
                st.rr, ob, qi, en_q & found_q, t0, nxt_q, self.rcfg))
            give_up = en_q & ~found_q
            st = dataclasses.replace(
                st, rr=rt_mod.drop_slot(st.rr, qi, give_up))
            routedrop_cnt += give_up.astype(I32)

        # ------------------------------------------------- completions -----
        new_lk, comp = lk_mod.take_completions(st.lk, t_end)
        st = dataclasses.replace(st, lk=new_lk)
        comp_hops_ev = (comp["hops"].astype(jnp.float32),
                        comp["taken"] & comp["success"])
        for li in range(lcfg.slots):
            en = comp["taken"][li]
            suc = comp["success"][li] & (comp["result"][li] != NO_NODE)
            res = comp["result"][li]
            pur = comp["purpose"][li]
            lksucc_cnt += (en & suc).astype(I32)
            anyfail_cnt += (en & ~suc).astype(I32)

            # join lookup done → request state from the responsible node
            enj = en & (pur == P_JOIN)
            ob.send(enj & suc, t0, res, wire.PASTRY_STATE_CALL,
                    stamp=t0, size_b=wire.BASE_CALL_B)
            # join lookup failed → retry
            st = dataclasses.replace(st, t_join=jnp.where(
                enj & ~suc, t0 + jnp.int64(int(p.join_delay * NS)),
                st.t_join))

            # tuning lookups: results already learned via responses

            # app lookups
            ena = en & (pur == P_APP)
            st = dataclasses.replace(st, app=self.app.on_lookup_done(
                st.app, app_base.LookupDone(
                    en=ena, success=ena & suc, tag=comp["aux"][li],
                    target=comp["target"][li], results=comp["results"][li],
                    hops=comp["hops"][li], t0=comp["t0"][li]),
                ctx, ob, ev, t0, node_idx))

        # ------------------------------------------------------- pump ------
        # getNodeTimeout (NeighborCache.cc:802) per destination
        timeout_fn = (nc_mod.adaptive_timeout_fn(st.nc, lcfg.rpc_timeout_ns)
                      if p.adaptive_timeouts else None)
        new_lk, _ = lk_mod.pump(st.lk, ob, ctx, node_idx, t0, rngs[0], lcfg,
                                timeout_fn=timeout_fn,
                                prox_fn=(nc_mod.prox_fn(st.nc)
                                         if lcfg.prox_aware else None))
        st = dataclasses.replace(st, lk=new_lk)

        # ------------------------------------------------------ events -----
        # Common API update() (BaseOverlay::callUpdate → BaseApp::update,
        # BaseApp.h:223): nodes that entered the leafset — Pastry's
        # replica/sibling set — trigger app re-replication
        if hasattr(self.app, "on_update"):
            new_leaf = jnp.concatenate([st.leaf_cw, st.leaf_ccw])
            new_in = jnp.where(
                (new_leaf != NO_NODE)
                & ~jnp.any(new_leaf[:, None] == old_leaf[None, :], axis=1),
                new_leaf, NO_NODE)
            st = dataclasses.replace(st, app=self.app.on_update(
                st.app, st.state == READY, ctx, ob, ev, t0, node_idx,
                new_in,
                sib_keys=ctx.keys[jnp.maximum(new_leaf, 0)],
                sib_valid=new_leaf != NO_NODE))

        events = {
            "c:pastry_joins": joins_cnt,
            "c:lookup_success": lksucc_cnt,
            "c:lookup_failed": anyfail_cnt,
            "c:route_dropped": routedrop_cnt,
            "s:lookup_hops": comp_hops_ev,
        }
        ev.finish(events, self.app.hist_map)
        return st, ob, events


def bamboo_params() -> PastryParams:
    """Bamboo defaults (default.ini:251-267): smaller leafset, periodic
    push maintenance (already the maintenance style here)."""
    return PastryParams(num_leaves=8)


class BambooLogic(PastryLogic):
    """Bamboo (src/overlay/bamboo/Bamboo.{h,cc}): Pastry variant whose
    maintenance is periodic push-pull instead of reactive repair — which
    is exactly this implementation's native style (module docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: PastryParams | None = None,
                 lcfg: lk_mod.LookupConfig | None = None,
                 app=None):
        super().__init__(spec, params or bamboo_params(), lcfg, app)

    def stat_spec(self) -> stats_mod.StatSpec:
        return super().stat_spec()
