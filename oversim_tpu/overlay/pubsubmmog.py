"""PubSubMMOG — grid-subspace publish/subscribe game overlay.

TPU-native rebuild of src/overlay/pubsubmmog/ (PubSubMMOG.{h,cc} 2.2k
LoC + PubSubLobby.{h,cc}): the play field is an
``numSubspaces x numSubspaces`` grid of subspaces
(PubSubSubspaceId.h:57); a central lobby server assigns one
*responsible node* per active subspace (PubSubLobby::handleRespCall,
PubSubLobby.cc) and players subscribe to every subspace overlapping
their AOI square (PubSubMMOG::handleMove AOI scan).  Movement updates
go to the current subspace's responsible node, which aggregates each
timeslot's moves (movementRate slots/s) and disseminates the move list
to all subscribers (PubSubMoveListMessage; sendMessageToChildren); a
move list older than maxMoveDelay counts as a wrong-timeslot delivery
(numEventsWrongTimeslot).

Redesign notes (vectorized engine, LogicBase gather/scatter):

  * **the lobby is global state, not a host** — the reference's lobby
    is an always-on server every player knows (PubSubMMOG.h:117
    ``lobbyServer``); here its player/duty maps live in the logic's
    glob part: a ``resp[S]`` responsible-node table maintained by the
    un-vmapped post_step from per-node "want" events.  Assignment
    latency collapses from one RPC round-trip to one tick (~the same
    10-20 ms), and lobby failure is out of scope in both builds.
  * **failure recovery via the lobby's global view**: the reference
    keeps a backup node per subspace plus ping/replacement chatter
    (PubSubBackupCall/PubSubReplacementMessage); here post_step sees
    ``ctx.alive`` directly (the same information the reference lobby
    re-learns through timeouts) and clears dead responsibles, so the
    next want-event reassigns the duty.  Children notice a silent
    parent after parentTimeout and re-request (handleParentTimeout).
  * **no intermediate load-balancing tree**: the reference inserts
    intermediate fan-out nodes above maxChildren subscribers
    (PubSubIntermediateCall, maxChildren default.ini:324).  Here a
    responsible node serves at most CH children and *rejects* further
    subscriptions (the player retries; the lobby may hand the duty of
    a neighboring subspace to someone else) — the per-node bandwidth
    cap the tree protects is modeled by the underlay's send queue, and
    the bounded-children reject keeps the same cap without tree
    state.  Subspace move lists ride one message per child per slot.

Wire mapping: a=subspace id, b=timeslot, nodes=mover slots.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps import movement as move_mod
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
F32 = jnp.float32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

DEAD, JOINING, READY = 0, 1, 2

PS_SUB_CALL = 125    # a=subspace — subscribe me
PS_SUB_RES = 126     # a=subspace, c=1 ok / 0 rejected (children full)
PS_UNSUB = 127       # a=subspace
PS_MOVE = 128        # a=subspace, b=timeslot, stamp=send time
PS_MOVELIST = 129    # a=subspace, b=timeslot, nodes=movers, stamp=slot t0


@dataclasses.dataclass(frozen=True)
class PubSubParams:
    """Reference params: PubSubMMOG.ned:30-39 + default.ini:321-326."""

    field: float = 1000.0        # areaDimension
    grid: int = 4                # numSubspaces (per direction)
    aoi: float = 100.0           # AOIWidth
    move_rate: float = 2.0       # movementRate (timeslots per second)
    speed: float = 5.0           # movementSpeed (units/s)
    join_delay: float = 1.0      # joinDelay
    parent_timeout: float = 2.0  # parentTimeout
    max_move_delay: float = 1.0  # maxMoveDelay
    max_children: int = 12       # maxChildren (also the CH array cap)
    duties: int = 4              # subspace duties one node may hold
    subs: int = 4                # subscription slots (AOI ≤ subspace →
                                 # at most 4 overlapping subspaces)
    generator: str = "randomRoaming"

    @property
    def nsub(self) -> int:
        return self.grid * self.grid

    @property
    def sub_size(self) -> float:
        return self.field / self.grid


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PubSubGlob:
    resp: jnp.ndarray        # [S] i32 responsible node per subspace
    age: jnp.ndarray         # [S] i32 ticks since assignment (grace for
                             # the assignee to adopt the duty)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PubSubState:
    """[N, ...] at rest; step() sees one node's slice."""

    state: jnp.ndarray       # [N]
    pos: jnp.ndarray         # [N, 2] f32
    wp: jnp.ndarray          # [N, 2] f32 waypoint
    # subscriber side
    sub_id: jnp.ndarray      # [N, SB] i32 subspace ids (-1 free)
    sub_ok: jnp.ndarray      # [N, SB] bool — subscription confirmed
    sub_seen: jnp.ndarray    # [N, SB] i64 — last move list from parent
    want: jnp.ndarray        # [N] i32 — subspace asked from the lobby
    # responsible side
    duty: jnp.ndarray        # [N, D] i32 subspace ids (-1 free)
    child: jnp.ndarray       # [N, D, CH] i32 subscribers
    mover: jnp.ndarray       # [N, D, CH] i32 — this slot's movers
    mv_n: jnp.ndarray       # [N, D] i32
    t_join: jnp.ndarray      # [N] i64
    t_slot: jnp.ndarray      # [N] i64 — next timeslot boundary
    slot_no: jnp.ndarray     # [N] i32
    glob: object             # PubSubGlob


class PubSubMMOGLogic:
    """Engine logic (interface: engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: PubSubParams = PubSubParams()):
        self.key_spec = spec
        self.p = params
        self.mp = move_mod.MoveParams(generator=params.generator,
                                      field=params.field,
                                      speed=params.speed)

    def stat_spec(self):
        return stats_mod.StatSpec(
            scalars=("ps_children",),
            hists=(),
            counters=("ps_joins", "ps_moves", "ps_lists_sent",
                      "ps_lists_recv", "ps_events_ok", "ps_events_late",
                      "ps_lost_lists", "ps_rejects"))

    # ------------------------------------------------ LogicBase glue ---
    def split(self, st):
        return dataclasses.replace(st, glob=None), st.glob

    def merge(self, node_part, glob):
        return dataclasses.replace(node_part, glob=glob)

    def post_step(self, ctx, st, events):
        """The lobby: clear dead responsibles, assign wanted subspaces.

        Mirrors PubSubLobby::handleRespCall (assign on demand, prefer
        the requester) + failedNode (drop duties of dead nodes)."""
        g: PubSubGlob = st.glob
        s = g.resp.shape[0]
        alive_resp = (g.resp != NO_NODE) & ctx.alive[
            jnp.maximum(g.resp, 0)]
        # an assignee that never adopted the duty (moved away before the
        # assignment landed) is dropped after a short grace
        held = jnp.any(
            st.duty[jnp.maximum(g.resp, 0)] ==
            jnp.arange(s, dtype=I32)[:, None], axis=-1)
        keep = alive_resp & (held | (g.age < 100))
        resp = jnp.where(keep, g.resp, NO_NODE)
        age = jnp.where(keep, g.age + 1, 0)
        want = events.get("g:ps_want")
        if want is not None:
            n = want.shape[0]
            # last requester per subspace wins the vacant duty
            cand = jnp.full((s,), NO_NODE, I32).at[
                jnp.where(want >= 0, jnp.clip(want, 0, s - 1), s)].set(
                    jnp.arange(n, dtype=I32), mode="drop")
            assign = (resp == NO_NODE) & (cand != NO_NODE)
            resp = jnp.where(assign, cand, resp)
            age = jnp.where(assign, 0, age)
        return dataclasses.replace(st, glob=PubSubGlob(resp=resp,
                                                       age=age))

    # ------------------------------------------------ engine hooks -----
    def init(self, rng, n: int) -> PubSubState:
        p = self.p
        pos, wp = move_mod.init_positions(rng, n, self.mp)
        return PubSubState(
            state=jnp.zeros((n,), I32),
            pos=pos, wp=wp,
            sub_id=jnp.full((n, p.subs), NO_NODE, I32),
            sub_ok=jnp.zeros((n, p.subs), bool),
            sub_seen=jnp.zeros((n, p.subs), I64),
            want=jnp.full((n,), NO_NODE, I32),
            duty=jnp.full((n, p.duties), NO_NODE, I32),
            child=jnp.full((n, p.duties, p.max_children), NO_NODE, I32),
            mover=jnp.full((n, p.duties, p.max_children), NO_NODE, I32),
            mv_n=jnp.zeros((n, p.duties), I32),
            t_join=jnp.full((n,), T_INF, I64),
            t_slot=jnp.full((n,), T_INF, I64),
            slot_no=jnp.zeros((n,), I32),
            glob=PubSubGlob(resp=jnp.full((p.nsub,), NO_NODE, I32),
                            age=jnp.zeros((p.nsub,), I32)))

    def reset(self, st, clear, join, t_now, rng):
        n = st.state.shape[0]
        glob = st.glob
        st = dataclasses.replace(st, glob=None)
        fresh = dataclasses.replace(self.init(rng, n), glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) *
                  self.p.join_delay * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st):
        return st.state == READY

    def next_event(self, st):
        t = jnp.where(st.state == JOINING, st.t_join, T_INF)
        t = jnp.minimum(t, jnp.where(st.state == READY, st.t_slot, T_INF))
        return t

    # ------------------------------------------------ helpers ----------
    def _subspace_of(self, pos):
        """[2] f32 → i32 grid cell id."""
        p = self.p
        c = jnp.clip((pos / p.sub_size).astype(I32), 0, p.grid - 1)
        return c[0] * p.grid + c[1]

    def _aoi_subspaces(self, pos):
        """[SB] i32: ids of the ≤4 subspaces the AOI square overlaps
        (the reference scans currentRegion ± AOIWidth)."""
        p = self.p
        half = p.aoi / 2.0
        ids = []
        for dx, dy in ((-1, -1), (-1, 1), (1, -1), (1, 1)):
            q = pos + jnp.asarray([dx * half, dy * half], F32)
            q = jnp.clip(q, 0.0, p.field - 1e-3)
            c = jnp.clip((q / p.sub_size).astype(I32), 0, p.grid - 1)
            ids.append(c[0] * p.grid + c[1])
        out = jnp.stack(ids)
        # dedupe (corners may share a cell): later duplicates → -1
        dup = jnp.zeros((4,), bool)
        for i in range(1, 4):
            dup = dup.at[i].set(jnp.any(out[:i] == out[i]))
        return jnp.where(dup, NO_NODE, out)

    # ------------------------------------------------ the step ---------
    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, spec = self.p, self.key_spec
        d_max, ch, sb = p.duties, p.max_children, p.subs
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        t0, t_end = ctx.t_start, ctx.t_end
        ev = app_base.AppEvents()
        glob: PubSubGlob = ctx.glob
        slot_ns = jnp.int64(int(NS / p.move_rate))
        c_joins = jnp.int32(0)
        c_moves = jnp.int32(0)
        c_sent = jnp.int32(0)
        c_recv = jnp.int32(0)
        c_ok = jnp.int32(0)
        c_late = jnp.int32(0)
        c_rej = jnp.int32(0)
        want_out = jnp.int32(NO_NODE)

        # ========================================= inbox handlers ======
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid
            is_ready = st.state == READY

            # ---- SUB_CALL: adopt a child for subspace a ------------
            di_ok = st.duty == m.a
            di = jnp.argmax(di_ok).astype(I32)
            en = v & (m.kind == PS_SUB_CALL) & is_ready & jnp.any(di_ok)
            crow = st.child[di]
            have = jnp.any(crow == m.src)
            free = jnp.any(crow == NO_NODE)
            slot = jnp.where(have, jnp.argmax(crow == m.src),
                             jnp.argmax(crow == NO_NODE)).astype(I32)
            adopt = en & (have | free)
            c_rej += (en & ~have & ~free).astype(I32)
            st = dataclasses.replace(st, child=st.child.at[
                jnp.where(adopt, di, d_max), slot].set(
                    m.src, mode="drop"))
            ob.send(en, now, m.src, PS_SUB_RES, a=m.a,
                    c=adopt.astype(I32), size_b=16)

            # ---- SUB_RES: subscription outcome ---------------------
            si_ok = st.sub_id == m.a
            si = jnp.argmax(si_ok).astype(I32)
            en = v & (m.kind == PS_SUB_RES) & jnp.any(si_ok)
            ok = en & (m.c != 0)
            fail = en & (m.c == 0)
            st = dataclasses.replace(
                st,
                sub_ok=st.sub_ok.at[jnp.where(ok, si, sb)].set(
                    True, mode="drop"),
                sub_seen=st.sub_seen.at[jnp.where(ok, si, sb)].set(
                    now, mode="drop"),
                # rejected: drop the slot; the AOI scan re-requests later
                sub_id=st.sub_id.at[jnp.where(fail, si, sb)].set(
                    NO_NODE, mode="drop"))

            # ---- UNSUB: drop the child -----------------------------
            di_ok = st.duty == m.a
            di = jnp.argmax(di_ok).astype(I32)
            en = v & (m.kind == PS_UNSUB) & jnp.any(di_ok)
            crow = st.child[di]
            ci = jnp.argmax(crow == m.src).astype(I32)
            hit = en & jnp.any(crow == m.src)
            st = dataclasses.replace(st, child=st.child.at[
                jnp.where(hit, di, d_max), ci].set(NO_NODE, mode="drop"))

            # ---- MOVE: collect the mover into this timeslot --------
            di_ok = st.duty == m.a
            di = jnp.argmax(di_ok).astype(I32)
            en = v & (m.kind == PS_MOVE) & is_ready & jnp.any(di_ok)
            c_moves += en.astype(I32)
            mrow = st.mover[di]
            have = jnp.any(mrow == m.src)
            slot = jnp.where(have, jnp.argmax(mrow == m.src),
                             jnp.argmax(mrow == NO_NODE)).astype(I32)
            put = en & (have | jnp.any(mrow == NO_NODE))
            st = dataclasses.replace(
                st,
                mover=st.mover.at[jnp.where(put, di, d_max), slot].set(
                    m.src, mode="drop"),
                mv_n=st.mv_n.at[jnp.where(put & ~have, di, d_max)].add(
                    1, mode="drop"))

            # ---- MOVELIST: the subspace's slot digest --------------
            si_ok = st.sub_id == m.a
            si = jnp.argmax(si_ok).astype(I32)
            en = v & (m.kind == PS_MOVELIST) & is_ready & jnp.any(si_ok)
            c_recv += en.astype(I32)
            nmv = jnp.sum(m.nodes[:ch] != NO_NODE, dtype=I32)
            late = now - m.stamp > jnp.int64(int(p.max_move_delay * NS))
            c_ok += jnp.where(en & ~late, nmv, 0)
            c_late += jnp.where(en & late, nmv, 0)
            st = dataclasses.replace(st, sub_seen=st.sub_seen.at[
                jnp.where(en, si, sb)].set(now, mode="drop"))

        # ========================================= timers ==============
        # ---- join: enter the field at the next slot boundary ----------
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        c_joins += en_j.astype(I32)
        st = dataclasses.replace(
            st,
            state=jnp.where(en_j, READY, st.state),
            t_slot=jnp.where(en_j, now_j + slot_ns, st.t_slot))

        # ---- timeslot: move, publish, AOI upkeep, duty digest ---------
        is_ready = st.state == READY
        en_s = is_ready & (st.t_slot < t_end)
        now_s = jnp.maximum(st.t_slot, t0)
        rng_wp, _ = jax.random.split(rng)

        # advance the position toward the waypoint (movement.py family)
        dt = jnp.where(en_s, 1.0 / p.move_rate, 0.0).astype(F32)
        delta = st.wp - st.pos
        dist = jnp.maximum(jnp.linalg.norm(delta), 1e-6)
        step_len = jnp.minimum(dist, p.speed * dt)
        pos = st.pos + delta / dist * step_len
        arrived = en_s & (dist <= p.speed * dt)
        wp = jnp.where(arrived, move_mod.draw_waypoints(
            rng_wp, pos, self.mp,
            t_s=ctx.t_start.astype(jnp.float32) / NS), st.wp)
        st = dataclasses.replace(st, pos=pos, wp=wp)

        cur = self._subspace_of(st.pos)
        aoi = self._aoi_subspaces(st.pos)           # [4] ids (-1 dups)

        # publish my move to the current subspace's responsible node
        resp_cur = glob.resp[jnp.clip(cur, 0, p.nsub - 1)]
        ob.send(en_s & (resp_cur != NO_NODE) & ctx.measuring, now_s,
                jnp.maximum(resp_cur, 0), PS_MOVE, a=cur, b=st.slot_no,
                stamp=now_s, size_b=40)

        # subscription upkeep: want AOI subspaces, drop stale ones
        # one new subscription request per slot (bounded signalling)
        in_aoi = jnp.zeros((sb,), bool)
        for ai in range(4):
            in_aoi = in_aoi | (st.sub_id == aoi[ai])
        # unsubscribe subspaces that left the AOI
        for si in range(sb):
            go = en_s & (st.sub_id[si] != NO_NODE) & ~in_aoi[si]
            rs = glob.resp[jnp.clip(st.sub_id[si], 0, p.nsub - 1)]
            ob.send(go & (rs != NO_NODE), now_s, jnp.maximum(rs, 0),
                    PS_UNSUB, a=st.sub_id[si], size_b=16)
            st = dataclasses.replace(
                st,
                sub_id=st.sub_id.at[jnp.where(go, si, sb)].set(
                    NO_NODE, mode="drop"),
                sub_ok=st.sub_ok.at[jnp.where(go, si, sb)].set(
                    False, mode="drop"))
        # parent timeout: confirmed subspace silent → re-request
        pto = jnp.int64(int(p.parent_timeout * NS))
        for si in range(sb):
            stale = (en_s & st.sub_ok[si] & (st.sub_id[si] != NO_NODE) &
                     (now_s - st.sub_seen[si] > pto))
            st = dataclasses.replace(
                st, sub_ok=st.sub_ok.at[jnp.where(stale, si, sb)].set(
                    False, mode="drop"))
        # adopt one missing AOI subspace into a free slot
        missing = jnp.full((1,), NO_NODE, I32)[0]
        for ai in range(4):
            known = jnp.any(st.sub_id == aoi[ai])
            missing = jnp.where((missing == NO_NODE) & (aoi[ai] >= 0) &
                                ~known, aoi[ai], missing)
        free_ok = jnp.any(st.sub_id == NO_NODE)
        fsi = jnp.argmax(st.sub_id == NO_NODE).astype(I32)
        put = en_s & (missing != NO_NODE) & free_ok
        st = dataclasses.replace(st, sub_id=st.sub_id.at[
            jnp.where(put, fsi, sb)].set(missing, mode="drop"))
        # (re)subscribe one unconfirmed slot: to the responsible if the
        # lobby has one, else raise a want-event so post_step assigns it
        # (PubSubLobby handleRespCall; the requester becomes responsible)
        need = (st.sub_id != NO_NODE) & ~st.sub_ok
        ni = jnp.argmax(need).astype(I32)
        has_need = en_s & jnp.any(need)
        ns_id = st.sub_id[jnp.clip(ni, 0, sb - 1)]
        rs = glob.resp[jnp.clip(ns_id, 0, p.nsub - 1)]
        ob.send(has_need & (rs != NO_NODE), now_s, jnp.maximum(rs, 0),
                PS_SUB_CALL, a=ns_id, size_b=16)
        want_out = jnp.where(has_need & (rs == NO_NODE), ns_id, NO_NODE)
        st = dataclasses.replace(
            st, sub_seen=st.sub_seen.at[
                jnp.where(has_need & (rs != NO_NODE), ni, sb)].set(
                    now_s, mode="drop"))

        # duty upkeep: if the lobby now lists me for a subspace I track,
        # nothing to do; if I hold a duty the lobby reassigned away,
        # drop it.  Adopt duties the lobby handed me (resp[s] == me).
        for di in range(d_max):
            sid = st.duty[di]
            lost = en_s & (sid != NO_NODE) & (
                glob.resp[jnp.clip(sid, 0, p.nsub - 1)] != node_idx)
            st = dataclasses.replace(
                st,
                duty=st.duty.at[jnp.where(lost, di, d_max)].set(
                    NO_NODE, mode="drop"),
                child=st.child.at[jnp.where(lost, di, d_max)].set(
                    jnp.full((ch,), NO_NODE, I32), mode="drop"),
                mover=st.mover.at[jnp.where(lost, di, d_max)].set(
                    jnp.full((ch,), NO_NODE, I32), mode="drop"))
        # adopt: scan the grid row that could name me (bounded: check
        # the AOI subspaces + current — the lobby only assigns duties
        # for subspaces we asked about)
        cand_ids = jnp.concatenate([aoi, cur[None]])
        for k in range(5):
            sid = cand_ids[k]
            mine = is_ready & (sid >= 0) & (
                glob.resp[jnp.clip(sid, 0, p.nsub - 1)] == node_idx)
            known = jnp.any(st.duty == sid)
            dfree = jnp.any(st.duty == NO_NODE)
            ddi = jnp.argmax(st.duty == NO_NODE).astype(I32)
            put = mine & ~known & dfree
            st = dataclasses.replace(st, duty=st.duty.at[
                jnp.where(put, ddi, d_max)].set(sid, mode="drop"))

        # duty digest: flush each duty's mover list to the children
        for di in range(d_max):
            act = en_s & (st.duty[di] != NO_NODE) & ctx.measuring
            has_mv = st.mv_n[di] > 0
            nch = jnp.sum(st.child[di] != NO_NODE, dtype=I32)
            ev.value("ps_children", nch.astype(F32), act)
            for ci in range(ch):
                cd = st.child[di, ci]
                snd = act & has_mv & (cd != NO_NODE)
                c_sent += snd.astype(I32)
                ob.send(snd, now_s, jnp.maximum(cd, 0), PS_MOVELIST,
                        a=st.duty[di], b=st.slot_no,
                        nodes=st.mover[di], stamp=now_s,
                        size_b=16 + 4 * ch)
            row = jnp.where(en_s, di, d_max)
            st = dataclasses.replace(
                st,
                mover=st.mover.at[row].set(
                    jnp.full((ch,), NO_NODE, I32), mode="drop"),
                mv_n=st.mv_n.at[row].set(0, mode="drop"))

        st = dataclasses.replace(
            st,
            slot_no=st.slot_no + en_s.astype(I32),
            t_slot=jnp.where(en_s, now_s + slot_ns, st.t_slot))

        events = {"c:ps_joins": c_joins, "c:ps_moves": c_moves,
                  "c:ps_lists_sent": c_sent, "c:ps_lists_recv": c_recv,
                  "c:ps_events_ok": c_ok, "c:ps_events_late": c_late,
                  "c:ps_lost_lists": jnp.int32(0),
                  "c:ps_rejects": c_rej,
                  "g:ps_want": want_out}
        ev.finish(events, {})
        return st, ob, events
