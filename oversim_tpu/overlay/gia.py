"""GIA capacity-aware unstructured overlay + search workload, vectorized.

TPU-native rebuild of the reference GIA (src/overlay/gia/Gia.{h,cc},
GiaNeighbors, GiaTokenFactory, GiaKeyList, + the GIASearchApp workload,
src/applications/giasearchapp/; params default.ini gia section:
minNeighbors/maxNeighbors, maxTopAdaptionInterval, tokenWaitTime,
maxResponses, keyListSize).  GIA is NOT a KBR overlay (kbr=false): there
is no key responsibility — searches are capacity-biased random walks.

State per node (structure-of-arrays):

  * ``capacity`` [N]: drawn from a power-law-ish spread over channel
    bandwidth classes (reference derives capacity from access bandwidth);
  * neighbor set [N, D] with degree bounds: topology adaptation keeps
    level-of-satisfaction S = Σ_j cap_j/deg_j / cap_i → 1 by acquiring
    neighbors while S < 1 (Gia.h:121-176 levelOfSatisfaction); acceptance
    at the receiver follows the GIA subset rule — accept if there is
    room, else accept iff the candidate's capacity exceeds the weakest
    neighbor's (dropping it with a disconnect notice);
  * token buckets [N, D]: each tokenInterval every node grants one
    forwarding token to a capacity-biased neighbor
    (GiaTokenFactory::sendToken); a query may only be forwarded to a
    neighbor we hold a token from, consuming it;
  * search (GIASearchApp): each node "shares" its own key; a periodic
    search draws a random live node's key (GlobalNodeList key-list
    oracle) and releases a biased random walk with maxResponses=1 and a
    TTL; any node whose key matches answers the originator directly;
    success ratio/hop count are recorded at the originator.

Search semantics follow Gia::processSearchMessage (Gia.cc:1147-1161):
a query is answered when the key is in the node's OWN key list *or any
neighbor's* key list (GIA one-hop replication — every node indexes its
neighbors' keys via periodic KeyListMessages, Gia.cc:395-410).  Here each
node shares exactly its node key, so the neighbor key index is the
``ctx.keys`` gather over the neighbor slots.  A query that cannot be
forwarded for lack of a token is NOT dropped: it is re-queued to self
with a token-wait delay (reference GiaMessageBookkeeping + tokenWaitTime)
and only dropped after ``token_wait_max`` requeues.

Simplifications vs the reference (documented): neighbor candidates are
drawn via the bootstrap oracle instead of PICK-neighbor random walks;
per-query visited-node bookkeeping (GiaMessageBookkeeping reverse paths)
is replaced by the TTL bound plus don't-send-back; one outstanding search
per node.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
F32 = jnp.float32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

DEAD, JOINING, READY = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class GiaParams:
    """default.ini gia namespace."""

    min_neighbors: int = 3        # minNeighbors
    max_neighbors: int = 10       # maxNeighbors (D axis bound)
    adapt_interval: float = 10.0  # maxTopAdaptionInterval
    token_interval: float = 2.0   # token generation period
    max_tokens: int = 10          # per-neighbor token cap
    search_interval: float = 60.0
    search_ttl: int = 20          # maxHopCount for walks
    max_responses: int = 1        # maxResponses
    search_timeout: float = 15.0
    join_delay: float = 5.0
    token_wait: float = 1.0       # tokenWaitTime — requeue delay when no
                                  # token edge is available
    token_wait_max: int = 5       # requeues before the query is dropped


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GiaState:
    state: jnp.ndarray      # [N] i32
    capacity: jnp.ndarray   # [N] f32
    nbr: jnp.ndarray        # [N, D] i32
    nbr_cap: jnp.ndarray    # [N, D] f32 — neighbor's advertised capacity
    tokens: jnp.ndarray     # [N, D] i32 — tokens we hold FROM neighbor d
    t_join: jnp.ndarray     # [N] i64
    t_adapt: jnp.ndarray    # [N] i64
    t_token: jnp.ndarray    # [N] i64
    t_search: jnp.ndarray   # [N] i64
    # one outstanding search
    s_active: jnp.ndarray   # [N] bool
    s_seq: jnp.ndarray      # [N] i32
    s_t0: jnp.ndarray       # [N] i64
    s_to: jnp.ndarray       # [N] i64


class GiaLogic:
    """Engine logic interface (no KBR: searches instead of lookups)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: GiaParams = GiaParams()):
        self.key_spec = spec
        self.p = params

    def stat_spec(self) -> stats_mod.StatSpec:
        return stats_mod.StatSpec(
            scalars=("gia_search_hops", "gia_search_latency_s",
                     "gia_satisfaction"),
            hists=(),
            counters=("gia_joins", "gia_searches", "gia_search_success",
                      "gia_search_failed", "gia_query_drops"))

    def init(self, rng, n: int) -> GiaState:
        p = self.p
        d = p.max_neighbors
        # capacity classes 1/10/100/1000 with decreasing probability
        # (reference assigns capacity by access-channel class)
        cls = jax.random.categorical(
            rng, jnp.log(jnp.asarray([0.5, 0.3, 0.15, 0.05])), shape=(n,))
        capacity = jnp.asarray([1.0, 10.0, 100.0, 1000.0], F32)[cls]
        return GiaState(
            state=jnp.zeros((n,), I32),
            capacity=capacity,
            nbr=jnp.full((n, d), NO_NODE, I32),
            nbr_cap=jnp.zeros((n, d), F32),
            tokens=jnp.zeros((n, d), I32),
            t_join=jnp.full((n,), T_INF, I64),
            t_adapt=jnp.full((n,), T_INF, I64),
            t_token=jnp.full((n,), T_INF, I64),
            t_search=jnp.full((n,), T_INF, I64),
            s_active=jnp.zeros((n,), bool),
            s_seq=jnp.zeros((n,), I32),
            s_t0=jnp.zeros((n,), I64),
            s_to=jnp.full((n,), T_INF, I64),
        )

    def split(self, st):
        return st, None

    def merge(self, node_part, glob):
        return node_part

    def post_step(self, ctx, st, events):
        return st

    def reset(self, st: GiaState, clear, join, t_now, rng):
        n = st.state.shape[0]
        r_init, r_j = jax.random.split(rng)
        fresh = self.init(r_init, n)
        # keep capacities stable for surviving nodes
        fresh = dataclasses.replace(fresh, capacity=jnp.where(
            clear, fresh.capacity, st.capacity))
        st = select_tree(clear, fresh, st)
        jitter = (jax.random.uniform(r_j, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: GiaState):
        return st.state == READY

    def next_event(self, st: GiaState):
        ready = st.state == READY
        t = jnp.where(st.state == JOINING, st.t_join, T_INF)
        for timer in (st.t_adapt, st.t_token, st.t_search):
            t = jnp.minimum(t, jnp.where(ready, timer, T_INF))
        t = jnp.minimum(t, jnp.where(st.s_active, st.s_to, T_INF))
        return t

    # -- per-node helpers -----------------------------------------------------

    def _deg(self, st):
        return jnp.sum((st.nbr != NO_NODE).astype(I32))

    def _satisfaction(self, st):
        """Gia::calculateLevelOfSatisfaction (Gia.cc:648-666): the mean
        neighbor capacity over own capacity, clamped — 0.0 below
        minNeighbors, 1.0 when >1 or at maxNeighbors."""
        deg = self._deg(st)
        total = jnp.sum(jnp.where(st.nbr != NO_NODE, st.nbr_cap, 0.0))
        los = total / (st.capacity * jnp.maximum(deg, 1).astype(F32))
        los = jnp.where(deg < self.p.min_neighbors, 0.0, los)
        los = jnp.where((los > 1.0) | (deg >= self.p.max_neighbors), 1.0,
                        los)
        return los

    def _nbr_add(self, st, peer, cap, en):
        """Insert into a free slot; returns (st, accepted, dropped_slot)."""
        free = st.nbr == NO_NODE
        has_free = jnp.any(free)
        already = jnp.any(st.nbr == peer)
        col_free = jnp.argmax(free).astype(I32)
        # subset rule: no room → replace the weakest if the candidate has
        # strictly higher capacity
        weakest = jnp.argmin(jnp.where(st.nbr != NO_NODE, st.nbr_cap,
                                       jnp.inf)).astype(I32)
        can_replace = ~has_free & (cap > st.nbr_cap[weakest])
        col = jnp.where(has_free, col_free, weakest)
        accept = en & ~already & (has_free | can_replace)
        dropped = jnp.where(accept & ~has_free, st.nbr[weakest], NO_NODE)
        col = jnp.where(accept, col, st.nbr.shape[0])
        st = dataclasses.replace(
            st,
            nbr=st.nbr.at[col].set(peer, mode="drop"),
            nbr_cap=st.nbr_cap.at[col].set(cap, mode="drop"),
            tokens=st.tokens.at[col].set(0, mode="drop"))
        return st, accept, dropped

    def _nbr_drop(self, st, peer):
        hit = st.nbr == peer
        return dataclasses.replace(
            st,
            nbr=jnp.where(hit, NO_NODE, st.nbr),
            nbr_cap=jnp.where(hit, 0.0, st.nbr_cap),
            tokens=jnp.where(hit, 0, st.tokens))

    def _forward_target(self, st, rng, exclude):
        """Pick the highest-capacity neighbor holding a token, excluding
        ``exclude`` (biased random walk, Gia::forwardSearchMessage)."""
        ok = (st.nbr != NO_NODE) & (st.tokens > 0) & (st.nbr != exclude)
        score = jnp.where(ok, st.nbr_cap, -1.0)
        # capacity-weighted random choice among token holders
        g = jax.random.gumbel(rng, score.shape)
        pick = jnp.argmax(jnp.where(ok, jnp.log(score + 1e-3) + g, -jnp.inf))
        has = jnp.any(ok)
        return jnp.where(has, st.nbr[pick], NO_NODE), pick.astype(I32), has

    # -- the per-node step ----------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, spec = self.p, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rngs = jax.random.split(rng, 8)
        t0 = ctx.t_start
        t_end = ctx.t_end

        joins_cnt = jnp.int32(0)
        searches = jnp.int32(0)
        succ_cnt = jnp.int32(0)
        fail_cnt = jnp.int32(0)
        drop_cnt = jnp.int32(0)
        hops_vals, hops_mask = [], []
        lat_vals, lat_mask = [], []

        # ------------------------------------------------------- inbox -----
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid

            # neighbor connect request (GiaNeighborMessage)
            en = v & (m.kind == wire.GIA_NEIGHBOR_CALL) & (
                st.state == READY)
            cap = m.a.astype(F32) / 16.0
            st2, accept, dropped = self._nbr_add(st, m.src, cap, en)
            st = st2
            ob.send(en & accept & (dropped != NO_NODE), now, dropped,
                    wire.GIA_DISCONNECT, size_b=wire.BASE_CALL_B)
            ob.send(en, now, m.src, wire.GIA_NEIGHBOR_RES,
                    a=(st.capacity * 16.0).astype(I32),
                    c=accept.astype(I32), size_b=wire.BASE_CALL_B + 8)

            # neighbor connect response
            en = v & (m.kind == wire.GIA_NEIGHBOR_RES) & (m.c != 0)
            cap = m.a.astype(F32) / 16.0
            st2, _, dropped = self._nbr_add(st, m.src, cap, en)
            st = st2
            ob.send(en & (dropped != NO_NODE), now, dropped,
                    wire.GIA_DISCONNECT, size_b=wire.BASE_CALL_B)
            # first accepted neighbor while joining → READY
            got = en & (st.state == JOINING)
            joins_cnt += got.astype(I32)
            st = dataclasses.replace(
                st,
                state=jnp.where(got, READY, st.state),
                t_join=jnp.where(got, T_INF, st.t_join),
                t_adapt=jnp.where(got, now, st.t_adapt),
                t_token=jnp.where(got, now, st.t_token),
                t_search=jnp.where(
                    got, now + (jax.random.uniform(rngs[0])
                                * p.search_interval * NS).astype(I64),
                    st.t_search))

            # disconnect notice
            en = v & (m.kind == wire.GIA_DISCONNECT)
            st = select_tree(en, self._nbr_drop(st, m.src), st)

            # token grant (GiaTokenFactory::sendToken)
            en = v & (m.kind == wire.GIA_TOKEN)
            col = jnp.argmax(st.nbr == m.src).astype(I32)
            is_nbr = jnp.any(st.nbr == m.src)
            col = jnp.where(en & is_nbr, col, st.nbr.shape[0])
            st = dataclasses.replace(st, tokens=st.tokens.at[col].set(
                jnp.minimum(st.tokens[jnp.minimum(col, st.nbr.shape[0] - 1)]
                            + 1, p.max_tokens), mode="drop"))

            # search query walk (Gia::processSearchMessage, Gia.cc:1147):
            # answer if the key is ours OR any neighbor's (one-hop
            # replication over the neighbor key index), else forward along
            # a token edge.  No token → requeue to self after tokenWaitTime
            # (GiaMessageBookkeeping), up to token_wait_max times.
            # Wire fields: a=originator, b=seq, c=prev-hop+1 (requeue
            # carry), d=token-wait count.
            en = v & (m.kind == wire.GIA_QUERY) & (st.state == READY)
            nbr_keys = ctx.keys[jnp.maximum(st.nbr, 0)]
            hit_nbr = jnp.any((st.nbr != NO_NODE)
                              & K.eq(jnp.broadcast_to(m.key, nbr_keys.shape),
                                     nbr_keys))
            hit = K.eq(m.key, me_key) | hit_nbr
            ob.send(en & hit, now, m.a, wire.GIA_QUERY_RES, key=m.key,
                    b=m.b, hops=m.hops, stamp=m.stamp,
                    size_b=wire.BASE_CALL_B + 20)
            prev_hop = jnp.where(m.c > 0, m.c - 1, m.src)
            fwd = en & ~hit & (m.hops < p.search_ttl)
            tgt, col, has = self._forward_target(st, rngs[1 + (r % 4)],
                                                 prev_hop)
            ob.send(fwd & has, now, tgt, wire.GIA_QUERY, key=m.key,
                    a=m.a, b=m.b, hops=m.hops + 1, stamp=m.stamp,
                    size_b=wire.BASE_CALL_B + 20 + 8)
            col = jnp.where(fwd & has, col, st.nbr.shape[0])
            st = dataclasses.replace(st, tokens=st.tokens.at[col].add(
                -1, mode="drop"))
            # token starvation: park the query on ourselves for a
            # tokenWaitTime and retry (drop only after token_wait_max)
            requeue = fwd & ~has & (m.d < p.token_wait_max)
            ob.send(requeue, now + jnp.int64(int(p.token_wait * NS)),
                    node_idx, wire.GIA_QUERY, key=m.key, a=m.a, b=m.b,
                    c=prev_hop + 1, d=m.d + 1, hops=m.hops, stamp=m.stamp,
                    size_b=wire.BASE_CALL_B + 20 + 8)
            drop_cnt += (en & ~hit & ~(fwd & has) & ~requeue).astype(I32)

            # search response at the originator
            en = v & (m.kind == wire.GIA_QUERY_RES) & st.s_active & (
                m.b == st.s_seq)
            succ_cnt += en.astype(I32)
            hops_vals.append((m.hops + 1).astype(F32))
            hops_mask.append(en & ctx.measuring)
            lat_vals.append((now - m.stamp).astype(F32) / NS)
            lat_mask.append(en & ctx.measuring)
            st = dataclasses.replace(
                st,
                s_active=jnp.where(en, False, st.s_active),
                s_to=jnp.where(en, T_INF, st.s_to))

        # ------------------------------------------------------- timers ----
        # join: connect to a random ready node (oracle bootstrap; the
        # reference walks PICK messages — simplification, module doc)
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[5], node_idx)
        alone = en_j & (boot == NO_NODE)
        joins_cnt += alone.astype(I32)
        st = dataclasses.replace(
            st,
            state=jnp.where(alone, READY, st.state),
            t_join=jnp.where(
                alone, T_INF,
                jnp.where(en_j, now_j + jnp.int64(int(p.join_delay * NS)),
                          st.t_join)),
            t_adapt=jnp.where(alone, now_j, st.t_adapt),
            t_token=jnp.where(alone, now_j, st.t_token),
            t_search=jnp.where(alone, T_INF, st.t_search))
        ob.send(en_j & (boot != NO_NODE), now_j, boot,
                wire.GIA_NEIGHBOR_CALL,
                a=(st.capacity * 16.0).astype(I32),
                size_b=wire.BASE_CALL_B + 8)

        # topology adaptation (Gia::handleTimerEvent adaptation)
        en_t = (st.state == READY) & (st.t_adapt < t_end)
        now_t = jnp.maximum(st.t_adapt, t0)
        sat = self._satisfaction(st)
        deg = self._deg(st)
        want_more = en_t & ((sat < 1.0) | (deg < p.min_neighbors)) & (
            deg < p.max_neighbors)
        cand = ctx.sample_ready(rngs[6], node_idx)
        ob.send(want_more & (cand != NO_NODE) & (cand != node_idx), now_t,
                cand, wire.GIA_NEIGHBOR_CALL,
                a=(st.capacity * 16.0).astype(I32),
                size_b=wire.BASE_CALL_B + 8)
        st = dataclasses.replace(st, t_adapt=jnp.where(
            en_t, now_t + jnp.int64(int(p.adapt_interval * NS)),
            st.t_adapt))

        # token generation: grant to a capacity-biased neighbor
        en_k = (st.state == READY) & (st.t_token < t_end)
        now_k = jnp.maximum(st.t_token, t0)
        okn = st.nbr != NO_NODE
        g = jax.random.gumbel(rngs[7], okn.shape)
        pick = jnp.argmax(jnp.where(okn, jnp.log(st.nbr_cap + 1e-3) + g,
                                    -jnp.inf))
        has_n = jnp.any(okn)
        ob.send(en_k & has_n, now_k, st.nbr[pick], wire.GIA_TOKEN,
                size_b=wire.BASE_CALL_B)
        st = dataclasses.replace(st, t_token=jnp.where(
            en_k, now_k + jnp.int64(int(p.token_interval * NS)),
            st.t_token))

        # search timeout
        en_to = st.s_active & (st.s_to < t_end)
        fail_cnt += en_to.astype(I32)
        st = dataclasses.replace(
            st, s_active=jnp.where(en_to, False, st.s_active),
            s_to=jnp.where(en_to, T_INF, st.s_to))

        # periodic search (GIASearchApp::handleTimerEvent)
        # NODE_LEAVE parks the search timer (leaving nodes stop testing)
        st = dataclasses.replace(st, t_search=jnp.where(
            ctx.leaving[node_idx], T_INF, st.t_search))
        en_s = (st.state == READY) & (st.t_search < t_end) & ~st.s_active
        now_s = jnp.maximum(st.t_search, t0)
        victim = ctx.sample_ready(rngs[2])
        key = ctx.keys[jnp.maximum(victim, 0)]
        tgt, col, has = self._forward_target(st, rngs[3], NO_NODE)
        fire = en_s & (victim != NO_NODE) & (victim != node_idx) & has
        searches += fire.astype(I32)
        seq = st.s_seq + 1
        ob.send(fire, now_s, tgt, wire.GIA_QUERY, key=key, a=node_idx,
                b=seq, hops=0, stamp=now_s,
                size_b=wire.BASE_CALL_B + 20 + 8)
        col = jnp.where(fire, col, st.nbr.shape[0])
        st = dataclasses.replace(
            st,
            tokens=st.tokens.at[col].add(-1, mode="drop"),
            s_active=jnp.where(fire, True, st.s_active),
            s_seq=jnp.where(fire, seq, st.s_seq),
            s_t0=jnp.where(fire, now_s, st.s_t0),
            s_to=jnp.where(fire, now_s + jnp.int64(
                int(p.search_timeout * NS)), st.s_to),
            t_search=jnp.where(
                (st.state == READY) & (st.t_search < t_end),
                now_s + jnp.int64(int(p.search_interval * NS)),
                st.t_search))

        # ------------------------------------------------------ events -----
        hv = jnp.stack(hops_vals) if hops_vals else jnp.zeros((1,), F32)
        hm = jnp.stack(hops_mask) if hops_mask else jnp.zeros((1,), bool)
        lv = jnp.stack(lat_vals) if lat_vals else jnp.zeros((1,), F32)
        lm = jnp.stack(lat_mask) if lat_mask else jnp.zeros((1,), bool)
        events = {
            "c:gia_joins": joins_cnt,
            "c:gia_searches": searches,
            "c:gia_search_success": succ_cnt,
            "c:gia_search_failed": fail_cnt,
            "c:gia_query_drops": drop_cnt,
            "s:gia_search_hops": (hv, hm),
            "s:gia_search_latency_s": (lv, lm),
            "s:gia_satisfaction": (
                jnp.minimum(self._satisfaction(st), 10.0)[None].astype(F32),
                ((st.state == READY) & ctx.measuring)[None]),
        }
        return st, ob, events
