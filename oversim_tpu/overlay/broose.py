"""Broose de Bruijn DHT — XOR buckets + shift routing as vectorized logic.

TPU-native rebuild of the reference Broose
(src/overlay/broose/Broose.{h,cc} + BrooseBucket.{h,cc}; params
default.ini:294-303: bucketSize 8, rBucketSize 8, shiftingBits 2,
joinDelay 10s, refreshTime 180s, numberRetries 0, stab1 false,
stab2 true), per "Broose: A Practical Distributed Hashtable Based on the
De-Bruijn Topology" (Gai & Viennot).

State per node (structure-of-arrays; every bucket kept XOR-sorted to its
bucket key, so "closest" is entry 0 — reference BrooseBucket is a std::map
keyed by XOR distance, BrooseBucket.cc:70-135):

  * ``rb`` [N, 2^s, k'] — right buckets: contacts near (me >> s) + i·2^(B-s)
    for each of the 2^s prefixes i (BrooseBucket::initializeBucket,
    BrooseBucket.cc:49-68);
  * ``lb`` [N, 2^s·k'] — left bucket: contacts near (me << s);
  * ``bb`` [N, 7k]      — brother bucket: contacts near me; the k closest
    are the sibling set (keyInRange, BrooseBucket.cc:239-258).

Routing (Broose::findNode, Broose.cc:574-770): a lookup carries mutable
state with the message — routeKey, signed step, right/left flag, last hop
— in the lookup engine's opaque ext words (common/lookup.py; the Koorde
pattern).  On initialization the hop distance is estimated from the
longest shared prefix inside rBucket[0]/rBucket[1] (+1+userDist, rounded
up to a multiple of shiftingBits) and the direction alternates per lookup
(chooseLookup counter).  Each hop shifts ``shiftingBits`` bits into/out of
the route key and forwards to the contact closest (XOR) to the updated
route key from the L bucket (left), rBucket[prefix] (right), or the B
bucket (step 0 = brother lookup).  isSiblingFor(key) = B-bucket range
check: (key ^ me) <= XOR distance of the k-th closest brother.

Join (Broose::changeState / handleBucketResponseRpc, Broose.cc:133-264,
1010-1052): INIT routes 2^s BBucketCalls to the keys i·2^(B-s)+(me>>s)
(here: 2^s iterative lookups seeded at the bootstrap node, each followed
by a direct BUCKET_CALL to the responsible node); all 2^s responses →
RSET, which pulls L buckets from every R-bucket contact (half must answer)
→ BSET, which pulls L buckets from every brother (half must answer) →
READY.  Deviations (documented): the RSET/BSET call fan-out is paced at
``calls_per_tick`` per pacing-timer fire to respect the bounded outbox;
per-BucketCall timeouts are replaced by a per-state deadline
(``join_state_timeout``) that restarts the join from INIT — the
reference restarts on any BucketCall timeout (handleBucketTimeout,
Broose.cc:1055-1062).

Maintenance: every refreshTime/2 the stalest entries are pinged
(handleBucketTimerExpired, Broose.cc:318-341; bounded to ``ping_slots``
concurrent pings); a ping/FindNode timeout removes the node from all
buckets (routingTimeout with numberRetries=0, Broose.cc:1070-1079);
every inbound message refreshes its sender (routingAdd alive,
Broose.cc:914-916); FindNodeResponse contents are learned as unverified
contacts (handleRpcResponse, Broose.cc:928-933).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps.kbrtest import KbrTestApp
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.common import route as rt_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
UMAX = jnp.uint32(0xFFFFFFFF)

# lifecycle (Broose States INIT→RSET→BSET→READY, Broose.cc:145-253)
DEAD, INIT, RSET, BSET, READY = 0, 1, 2, 3, 4

# lookup purposes
P_JOINB, P_APP = 1, 3

# BucketCall proState tags (BrooseMessage.msg PINIT/PRSET/PBSET;
# PR_REFRESH is the periodic brother-bucket exchange)
PR_INIT, PR_RSET, PR_BSET, PR_REFRESH = 0, 1, 2, 3
# BucketCall bucket types
BT_BROTHER, BT_LEFT = 0, 1

SELF_HOPS = 2        # unrolled findNode self-recursion (Broose.cc:766-769)


@dataclasses.dataclass(frozen=True)
class BrooseParams:
    """default.ini:294-303."""

    bucket_size: int = 8          # k  — sibling count
    r_bucket_size: int = 8        # k' — per-prefix right bucket
    shifting_bits: int = 2
    user_dist: int = 0
    join_delay: float = 10.0
    refresh_time: float = 180.0
    number_retries: int = 0       # kept for parity; 0 = remove on timeout
    rpc_timeout: float = 1.5
    # engine-shape knobs (module docstring: deviations)
    calls_per_tick: int = 4       # RSET/BSET fan-out pace
    pace_delay: float = 0.5
    ping_slots: int = 4
    join_state_timeout: float = 20.0

    @property
    def pow_shift(self) -> int:
        return 1 << self.shifting_bits

    @property
    def lb_size(self) -> int:
        return self.pow_shift * self.r_bucket_size

    @property
    def bb_size(self) -> int:
        return 7 * self.bucket_size


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BrooseState:
    state: jnp.ndarray      # [N] i32
    rb: jnp.ndarray         # [N, 2^s, k'] i32
    rb_seen: jnp.ndarray    # [N, 2^s, k'] i64
    lb: jnp.ndarray         # [N, LB] i32
    lb_seen: jnp.ndarray    # [N, LB] i64
    bb: jnp.ndarray         # [N, BB] i32
    bb_seen: jnp.ndarray    # [N, BB] i64
    choose: jnp.ndarray     # [N] i32 — chooseLookup direction alternator
    t_join: jnp.ndarray     # [N] i64 — join + RSET/BSET pacing timer
    t_bucket: jnp.ndarray   # [N] i64 — refresh timer
    state_to: jnp.ndarray   # [N] i64 — join-state deadline
    jb_recv: jnp.ndarray    # [N] i32 — BROTHER responses (INIT)
    pr_recv: jnp.ndarray    # [N] i32 — PRSET responses
    pr_need: jnp.ndarray    # [N] i32
    pr_cursor: jnp.ndarray  # [N] i32 — next rb-flat index to call
    pb_recv: jnp.ndarray    # [N] i32 — PBSET responses
    pb_need: jnp.ndarray    # [N] i32
    pb_cursor: jnp.ndarray  # [N] i32
    ping_dst: jnp.ndarray   # [N, PP] i32
    ping_to: jnp.ndarray    # [N, PP] i64
    lk: lk_mod.LookupState
    rr: object              # rt_mod.RouteState — recursive-routing hook
    app: object
    app_glob: object


class BrooseLogic:
    """Engine logic interface (engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: BrooseParams = BrooseParams(),
                 lcfg: lk_mod.LookupConfig | None = None,
                 app=None,
                 rcfg: rt_mod.RouteConfig | None = None):
        """``rcfg`` switches the app data path to the recursive family
        like chord.py; the shift-routing ext (routeKey/step/flags/last,
        Broose.cc:622-668) rides the head of the routed message's nodes
        field (rcfg.ext_words is forced to match the lookup ext)."""
        self.key_spec = spec
        self.p = params
        ew = spec.lanes + 3
        self.lcfg = lcfg or lk_mod.LookupConfig(slots=8, ext_words=ew)
        if self.lcfg.ext_words != ew:
            raise ValueError("Broose needs ext_words == key lanes + 3")
        if params.shifting_bits > spec.top_lane_bits:
            raise ValueError("shiftingBits must fit in the top key lane")
        if rcfg is not None and rcfg.ext_words != ew:
            rcfg = dataclasses.replace(rcfg, ext_words=ew)
        self.rcfg = rcfg
        self.app = app or KbrTestApp()
        if rcfg is not None:
            app_rcfg = getattr(self.app, "rcfg", "no")
            if app_rcfg is None or (app_rcfg not in ("no",)
                                    and app_rcfg.ext_words != ew):
                # hand the ext-corrected config to the app's reply path
                self.app.rcfg = rcfg
        # static: keyLength rounded down to a shifting_bits multiple
        self.max_dist = spec.bits - spec.bits % params.shifting_bits

    # -- engine interface ---------------------------------------------------

    def stat_spec(self) -> stats_mod.StatSpec:
        app = self.app.stat_spec()
        return stats_mod.StatSpec(
            scalars=tuple(app["scalars"]) + ("lookup_hops",),
            hists=tuple(app["hists"]),
            counters=tuple(app["counters"]) + (
                "broose_joins", "broose_join_retries", "lookup_success",
                "lookup_failed", "route_dropped"),
        )

    def split(self, st: BrooseState):
        return dataclasses.replace(st, app_glob=None), st.app_glob

    def merge(self, node_part: BrooseState, glob):
        return dataclasses.replace(node_part, app_glob=glob)

    def post_step(self, ctx, st: BrooseState, events):
        app, glob = self.app.post_step(ctx, st.app, st.app_glob, events)
        return dataclasses.replace(st, app=app, app_glob=glob)

    def init(self, rng, n: int) -> BrooseState:
        p = self.p
        return BrooseState(
            state=jnp.zeros((n,), I32),
            rb=jnp.full((n, p.pow_shift, p.r_bucket_size), NO_NODE, I32),
            rb_seen=jnp.zeros((n, p.pow_shift, p.r_bucket_size), I64),
            lb=jnp.full((n, p.lb_size), NO_NODE, I32),
            lb_seen=jnp.zeros((n, p.lb_size), I64),
            bb=jnp.full((n, p.bb_size), NO_NODE, I32),
            bb_seen=jnp.zeros((n, p.bb_size), I64),
            choose=jnp.zeros((n,), I32),
            t_join=jnp.full((n,), T_INF, I64),
            t_bucket=jnp.full((n,), T_INF, I64),
            state_to=jnp.full((n,), T_INF, I64),
            jb_recv=jnp.zeros((n,), I32),
            pr_recv=jnp.zeros((n,), I32),
            pr_need=jnp.zeros((n,), I32),
            pr_cursor=jnp.zeros((n,), I32),
            pb_recv=jnp.zeros((n,), I32),
            pb_need=jnp.zeros((n,), I32),
            pb_cursor=jnp.zeros((n,), I32),
            ping_dst=jnp.full((n, p.ping_slots), NO_NODE, I32),
            ping_to=jnp.full((n, p.ping_slots), T_INF, I64),
            lk=jax.vmap(lambda _: lk_mod.init(self.lcfg, self.key_spec.lanes))(
                jnp.arange(n)),
            rr=jax.vmap(lambda _: rt_mod.init(
                self.rcfg or rt_mod.RouteConfig(), self.key_spec.lanes,
                16))(jnp.arange(n)),
            app=self.app.init(n),
            app_glob=self.app.glob_init(rng),
        )

    def reset(self, st: BrooseState, clear, join, t_now, rng) -> BrooseState:
        n = st.state.shape[0]
        glob = st.app_glob
        st = dataclasses.replace(st, app_glob=None)
        fresh = dataclasses.replace(self.init(rng, n), app_glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, app_glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, INIT, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: BrooseState):
        return st.state == READY

    def next_event(self, st: BrooseState):
        joining = (st.state >= INIT) & (st.state < READY)
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        t = jnp.minimum(t, st.state_to)
        t = jnp.minimum(t, jnp.where(ready, st.t_bucket, T_INF))
        t = jnp.minimum(t, jnp.min(st.ping_to, axis=-1))
        t = jnp.minimum(t, jnp.where(ready, self.app.next_event(st.app),
                                     T_INF))
        t = jnp.minimum(t, jax.vmap(lk_mod.next_event)(st.lk))
        if self.rcfg is not None:
            t = jnp.minimum(t, jax.vmap(rt_mod.next_event)(st.rr))
        return t

    # -- bucket machinery ---------------------------------------------------

    def _bucket_keys(self, me_key):
        """(rb_keys [2^s, KL], lb_key, bb_key) — BrooseBucket bucket keys
        (BrooseBucket::initializeBucket, BrooseBucket.cc:49-68)."""
        p, spec = self.p, self.key_spec
        shr = K.shr_const(me_key, p.shifting_bits, spec)
        rb_keys = jnp.stack([
            K.add(shr, K.from_int(i << (spec.bits - p.shifting_bits), spec),
                  spec)
            for i in range(p.pow_shift)])
        lb_key = K.shl_const(me_key, p.shifting_bits, spec)
        return rb_keys, lb_key, me_key

    def _xor_to(self, ctx, slots, key):
        ck = ctx.keys[jnp.maximum(slots, 0)]
        d = ck ^ jnp.broadcast_to(key, ck.shape)
        return jnp.where((slots == NO_NODE)[..., None], UMAX, d)

    def _bkt_put(self, ctx, bkey, arr, seen, cands, cseen):
        """Merge candidate slots into one XOR-sorted bucket row.

        ``cands`` [C] may contain NO_NODE/duplicates; existing entries win
        their stored lastSeen unless a candidate duplicates them with a
        newer one (BrooseBucket::add, BrooseBucket.cc:70-135: insert if
        closer than the current farthest or bucket not full)."""
        cap = arr.shape[0]
        aug = jnp.concatenate([arr, cands])
        aseen = jnp.concatenate([seen, cseen])
        # newer lastSeen for duplicated existing entries
        match = (arr[:, None] == cands[None, :]) & (cands != NO_NODE)[None, :]
        upd = jnp.max(jnp.where(match, cseen[None, :], 0), axis=1)
        aseen = aseen.at[:cap].set(jnp.maximum(seen, upd))
        dup = K.dup_mask(aug) | (aug == NO_NODE)
        aug = jnp.where(dup, NO_NODE, aug)
        d = self._xor_to(ctx, aug, bkey)
        _, (aug_s, seen_s) = K.sort_by_distance(d, (aug, aseen), approx=True)
        return aug_s[:cap], jnp.where(aug_s[:cap] == NO_NODE, 0, seen_s[:cap])

    def _routing_add(self, ctx, st, me_key, node_idx, cands, alive, now):
        """routingAdd to every bucket (Broose.cc:1081-1091).  ``cands``
        [C] slots, ``alive`` scalar or [C] bool."""
        p = self.p
        cands = jnp.atleast_1d(jnp.asarray(cands, I32))
        alive = jnp.broadcast_to(jnp.asarray(alive), cands.shape)
        cands = jnp.where(cands == node_idx, NO_NODE, cands)
        cseen = jnp.where(alive & (cands != NO_NODE), now, 0).astype(I64)
        rb_keys, lb_key, bb_key = self._bucket_keys(me_key)
        rb, rb_seen = jax.vmap(
            lambda bk, a, s: self._bkt_put(ctx, bk, a, s, cands, cseen))(
                rb_keys, st.rb, st.rb_seen)
        lb, lb_seen = self._bkt_put(ctx, lb_key, st.lb, st.lb_seen, cands,
                                    cseen)
        bb, bb_seen = self._bkt_put(ctx, bb_key, st.bb, st.bb_seen, cands,
                                    cseen)
        return dataclasses.replace(st, rb=rb, rb_seen=rb_seen, lb=lb,
                                   lb_seen=lb_seen, bb=bb, bb_seen=bb_seen)

    def _remove_node(self, ctx, st, me_key, node_idx, bad):
        """Drop ``bad`` [F] slots from all buckets and re-compact
        (routingTimeout with numberRetries=0, Broose.cc:1070-1079)."""
        bad = jnp.atleast_1d(bad)
        any_bad = jnp.any(bad != NO_NODE)

        def hit(x):
            return (x[..., None] == bad).any(-1) & (x != NO_NODE)

        rb = jnp.where(hit(st.rb), NO_NODE, st.rb)
        lb = jnp.where(hit(st.lb), NO_NODE, st.lb)
        bb = jnp.where(hit(st.bb), NO_NODE, st.bb)
        rb_keys, lb_key, bb_key = self._bucket_keys(me_key)
        none = jnp.full((1,), NO_NODE, I32)
        zer = jnp.zeros((1,), I64)
        rb, rb_seen = jax.vmap(
            lambda bk, a, s: self._bkt_put(ctx, bk, a, s, none, zer))(
                rb_keys, rb, st.rb_seen)
        lb, lb_seen = self._bkt_put(ctx, lb_key, lb, st.lb_seen, none, zer)
        bb, bb_seen = self._bkt_put(ctx, bb_key, bb, st.bb_seen, none, zer)
        return select_tree(
            any_bad,
            dataclasses.replace(st, rb=rb, rb_seen=rb_seen, lb=lb,
                                lb_seen=lb_seen, bb=bb, bb_seen=bb_seen),
            st)

    def _longest_prefix(self, ctx, arr):
        """sharedPrefixLength of a bucket's closest and farthest entries
        (BrooseBucket::longestPrefix, BrooseBucket.cc:202-209); buckets
        are kept XOR-sorted so those are the first/last valid entries."""
        n = jnp.sum((arr != NO_NODE).astype(I32))
        first = arr[0]
        last = arr[jnp.clip(n - 1, 0, arr.shape[0] - 1)]
        spl = K.shared_prefix_length(
            ctx.keys[jnp.maximum(first, 0)], ctx.keys[jnp.maximum(last, 0)],
            self.key_spec)
        return jnp.where(n < 2, 0, spl).astype(I32)

    def _is_sibling(self, ctx, st, me_key, key):
        """bBucket keyInRange (BrooseBucket.cc:239-258): true when
        (key ^ me) <= XOR distance of the k-th closest brother.

        The reference inserts thisNode into every bucket on READY
        (changeState(READY), Broose.cc:237-240); here self is an implicit
        rank-0 member (XOR distance 0), so the k-th closest overall is
        the stored bucket's (k-1)-th entry — and a lone bootstrap node
        (empty bb) is sibling for everything."""
        p, spec = self.p, self.key_spec
        nb = jnp.sum((st.bb != NO_NODE).astype(I32)) + 1   # + self
        kth = st.bb[jnp.clip(p.bucket_size - 2, 0, p.bb_size - 1)]
        dist = ctx.keys[jnp.maximum(kth, 0)] ^ me_key
        close = K.le(key ^ me_key, dist)
        return (st.state == READY) & ((nb <= p.bucket_size) | close)

    # -- findNode (Broose.cc:574-770) ---------------------------------------

    def _unpack_ext(self, ext):
        spec = self.key_spec
        rk = jax.lax.bitcast_convert_type(ext[:spec.lanes], U32)
        return rk, ext[spec.lanes], ext[spec.lanes + 1], ext[spec.lanes + 2]

    def _pack_ext(self, rk, step, flags, last):
        return jnp.concatenate([
            jax.lax.bitcast_convert_type(rk, I32),
            jnp.stack([jnp.asarray(step, I32), jnp.asarray(flags, I32),
                       jnp.asarray(last, I32)])])

    def _init_ext(self, ctx, st, me_key, node_idx, key):
        """First findNode evaluation initializes the ext (Broose.cc:622-668):
        estimate the hop distance from the R buckets' longest shared
        prefixes and alternate the shifting direction per lookup."""
        p, spec = self.p, self.key_spec
        s = p.shifting_bits
        dist = jnp.maximum(self._longest_prefix(ctx, st.rb[0]),
                           self._longest_prefix(ctx, st.rb[1])) + 1 \
            + p.user_dist
        dist = dist + (s - dist % s) % s
        dist = jnp.minimum(dist, self.max_dist)
        left = st.choose % 2 == 0
        # left: routeKey = (key >> dist) + me's top dist bits in place
        me_top = K.shl_dyn(K.shr_dyn(me_key, spec.bits - dist, spec),
                           spec.bits - dist, spec)
        rk_left = K.add(K.shr_dyn(key, dist, spec), me_top, spec)
        rk = jnp.where(left, rk_left, me_key)
        step = jnp.where(left, -dist, dist)
        flags = jnp.where(left, 1, 3).astype(I32)   # bit0 init, bit1 right
        return rk, step, flags

    def _eval_once(self, ctx, st, me_key, node_idx, key, rk, step, right,
                   rmax):
        """One shifting-hop evaluation: returns (res [rmax] sorted
        candidates, rk', step')."""
        p, spec = self.p, self.key_spec
        s = p.shifting_bits
        brother = step == 0
        # left hop (Broose.cc:697-727)
        rk_l = K.shl_const(rk, s, spec)
        step_l = step + s
        # right hop (Broose.cc:728-764): prefix = s key bits at MSB
        # positions [dist-s, dist-1] → MSB digit index dist/s - 1
        di = jnp.maximum(step // s - 1, 0)
        pfx = K.digit(key, di, s, spec)
        top = jnp.zeros((spec.lanes,), U32).at[0].set(
            pfx.astype(U32) << (spec.top_lane_bits - s))
        rk_r = K.add(K.shr_const(rk, s, spec), top, spec)
        step_r = step - s

        rk2 = jnp.where(brother, rk, jnp.where(right, rk_r, rk_l))
        step2 = jnp.where(brother, step, jnp.where(right, step_r, step_l))
        # candidate bucket: bb (brother) / rb[pfx] (right) / lb (left),
        # plus self; sorted by XOR to key (brother) or new route key
        pad = max(p.bb_size, p.lb_size, p.r_bucket_size) + 1

        def padded(v):
            return jnp.concatenate(
                [v, jnp.full((pad - v.shape[0],), NO_NODE, I32)])

        cands = jnp.where(
            brother, padded(jnp.concatenate([st.bb, node_idx[None]])),
            jnp.where(right,
                      padded(jnp.concatenate([st.rb[pfx], node_idx[None]])),
                      padded(jnp.concatenate([st.lb, node_idx[None]]))))
        sort_key = jnp.where(brother, key, rk2)
        d = self._xor_to(ctx, cands, sort_key)
        d = jnp.where(K.dup_mask(cands)[:, None], UMAX, d)
        _, (cands_s,) = K.sort_by_distance(d, (cands,), approx=True)
        res = cands_s[:rmax]
        if res.shape[0] < rmax:
            res = jnp.concatenate(
                [res, jnp.full((rmax - res.shape[0],), NO_NODE, I32)])
        return res, rk2, step2

    def _eval_find(self, ctx, st, me_key, node_idx, key, ext, rmax):
        """Full findNode evaluation against this node's buckets.

        Returns (res [rmax], is_sib, ext_out, answerable, inited).
        ``answerable`` is false in INIT/RSET (reference findNode returns
        an empty vector, Broose.cc:578-580) and for left-shifting hops in
        BSET (Broose.cc:699-701)."""
        p, spec = self.p, self.key_spec
        rk_in, step_in, flags, _last = self._unpack_ext(ext)
        need_init = (flags & 1) == 0
        rk0, step0, flags0 = self._init_ext(ctx, st, me_key, node_idx, key)
        rk = jnp.where(need_init, rk0, rk_in)
        step = jnp.where(need_init, step0, step_in)
        flags = jnp.where(need_init, flags0, flags)
        right = (flags & 2) != 0

        is_sib = self._is_sibling(ctx, st, me_key, key)
        # sibling result: brothers + self by XOR to key (Broose.cc:598-620)
        sib_set, _, _ = self._eval_once(ctx, st, me_key, node_idx, key,
                                        rk, jnp.int32(0), right, rmax)

        # self-recursion unrolled (Broose.cc:766-769): while the best
        # candidate is this node itself, take another shifting hop
        res, rk_c, step_c = self._eval_once(ctx, st, me_key, node_idx, key,
                                            rk, step, right, rmax)
        for _ in range(SELF_HOPS - 1):
            again = res[0] == node_idx
            res2, rk2, step2 = self._eval_once(ctx, st, me_key, node_idx,
                                               key, rk_c, step_c, right,
                                               rmax)
            res = jnp.where(again, res2, res)
            rk_c = jnp.where(again, rk2, rk_c)
            step_c = jnp.where(again, step2, step_c)

        left_hop = ~right & (step != 0)
        answerable = ((st.state == READY)
                      | ((st.state == BSET) & ~left_hop))
        res = jnp.where(answerable, res, NO_NODE)
        ext_out = self._pack_ext(rk_c, step_c, flags, node_idx)
        return jnp.where(is_sib, sib_set, res), is_sib, ext_out, answerable, \
            need_init

    # -- failure/ready hooks ------------------------------------------------

    def _handle_failed(self, ctx, st, me_key, node_idx, failed):
        return self._remove_node(ctx, st, me_key, node_idx, failed)

    def _restart_join_node(self, st, en, now, rng):
        """Back to INIT: clear buckets and counters, redraw bootstrap at
        the next join-timer fire (changeState(INIT), Broose.cc:148-173).
        Per-node form (all leaves are one node's slice)."""
        jitter = (jax.random.uniform(rng, ()) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(en, INIT, st.state),
            rb=jnp.where(en, NO_NODE, st.rb),
            rb_seen=jnp.where(en, 0, st.rb_seen),
            lb=jnp.where(en, NO_NODE, st.lb),
            lb_seen=jnp.where(en, 0, st.lb_seen),
            bb=jnp.where(en, NO_NODE, st.bb),
            bb_seen=jnp.where(en, 0, st.bb_seen),
            jb_recv=jnp.where(en, 0, st.jb_recv),
            pr_recv=jnp.where(en, 0, st.pr_recv),
            pb_recv=jnp.where(en, 0, st.pb_recv),
            t_join=jnp.where(en, now + jitter, st.t_join),
            state_to=jnp.where(en, T_INF, st.state_to))

    def _become_ready(self, ctx, st, en, now, rng):
        p = self.p
        return dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            t_join=jnp.where(en, T_INF, st.t_join),
            state_to=jnp.where(en, T_INF, st.state_to),
            t_bucket=jnp.where(
                en, now + jnp.int64(int(p.refresh_time / 2 * NS)),
                st.t_bucket),
            app=self.app.on_ready(st.app, en, now, rng))

    def _paced_calls(self, st, ob, en, now, arr, cursor, pro_state):
        """Send up to calls_per_tick BUCKET_CALL(LEFT, pro_state) to the
        valid entries of ``arr`` starting at ``cursor`` (the paced RSET/
        BSET fan-out; module docstring).  Returns new cursor."""
        p = self.p
        valid = (arr != NO_NODE) & ~K.dup_mask(arr)
        idx = jnp.arange(arr.shape[0], dtype=I32)
        elig = valid & (idx >= cursor)
        cum = jnp.cumsum(elig.astype(I32))
        last_sent = cursor
        for j in range(p.calls_per_tick):
            pick = elig & (cum == j + 1)
            hit = jnp.any(pick)
            tgt = arr[jnp.argmax(pick)]
            ob.send(en & hit, now, tgt, wire.BROOSE_BUCKET_CALL,
                    a=jnp.int32(BT_LEFT), b=jnp.int32(pro_state),
                    size_b=wire.BASE_CALL_B + 2)
            last_sent = jnp.where(en & hit, idx[jnp.argmax(pick)] + 1,
                                  last_sent)
        return jnp.where(en, last_sent, cursor)

    # -- the per-node step ---------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, lcfg, spec = self.p, self.lcfg, self.key_spec
        s = p.shifting_bits
        ew = lcfg.ext_words
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rngs = jax.random.split(rng, 9)
        t0 = ctx.t_start
        t_end = ctx.t_end
        pace_ns = jnp.int64(int(p.pace_delay * NS))
        state_to_ns = jnp.int64(int(p.join_state_timeout * NS))

        def metric_fn(cand_slots, target):
            return self._xor_to(ctx, cand_slots, target)

        ev = app_base.AppEvents()
        joins_cnt = jnp.int32(0)
        retries_cnt = jnp.int32(0)
        anyfail_cnt = jnp.int32(0)
        lksucc_cnt = jnp.int32(0)
        routedrop_cnt = jnp.int32(0)

        me_key_pre = ctx.keys[node_idx]
        # recursive-route pre-pass (shared helpers, common/route.py):
        # each hop runs the shift-routing evaluation with the ext carried
        # in the head of the routed message's nodes field
        if self.rcfg is not None:
            def _route_find(mm_key, mm_nodes):
                res, sib, ext_out, ok, _ = self._eval_find(
                    ctx, st, me_key_pre, node_idx, mm_key,
                    mm_nodes[:ew], rmax)
                return jnp.where(sib, res,
                                 res.at[rmax - ew:].set(ext_out)), sib
            res_rt, sib_rt = jax.vmap(_route_find)(msgs.key, msgs.nodes)
            veto = ((lambda mm: self.app.forward(st.app, mm, ctx))
                    if hasattr(self.app, "forward") else None)
            new_rr, msgs, drop = rt_mod.prepass(
                st.rr, ob, msgs, res_rt, sib_rt,
                st.state >= BSET, node_idx, self.rcfg, forward_veto=veto)
            st = dataclasses.replace(st, rr=new_rr)
            routedrop_cnt += drop

        # ------------------------------------------------------- inbox -----
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid

            # every inbound message refreshes its sender (routingAdd alive,
            # Broose.cc:840-846, 914-916).  Gated on the sender being
            # READY: in the reference a joining node never emits FindNode
            # itself — its join calls are proxy-routed by the bootstrap
            # node (sendRouteRpcCall via bootstrapNode, Broose.cc:296-303)
            # — so joiners must not enter anyone's routing buckets, or
            # walks forward into non-answering INIT nodes and die
            st = select_tree(
                v & ctx.ready[jnp.maximum(m.src, 0)],
                self._routing_add(ctx, st, me_key, node_idx, m.src,
                                  jnp.bool_(True), now), st)

            # FindNodeCall → shift-routing evaluation.  Only BSET/READY
            # answer (handleRpcCall, Broose.cc:878-909)
            en = v & (m.kind == wire.FINDNODE_CALL)
            ext_in = m.nodes[:ew]
            res, sib, ext_out, ok, _ = self._eval_find(
                ctx, st, me_key, node_idx, m.key, ext_in, rmax)
            # learn the previous hop from the ext (Broose.cc:673-680;
            # READY-gated like every learn — see above)
            _, _, _, last = self._unpack_ext(ext_in)
            st = select_tree(
                en & (last != NO_NODE) & ctx.ready[jnp.maximum(last, 0)],
                self._routing_add(ctx, st, me_key, node_idx, last,
                                  jnp.bool_(True), now), st)
            res = jnp.where(sib, res, res.at[rmax - ew:].set(ext_out))
            n_res = jnp.sum((res != NO_NODE).astype(I32))
            ob.send(en & ok, now, m.src, wire.FINDNODE_RES, key=m.key,
                    a=m.a, b=m.b, c=sib.astype(I32), nodes=res,
                    size_b=wire.BASE_CALL_B + 1 + wire.NODEHANDLE_B * n_res)

            # FindNodeResponse → lookup engine + unverified learns
            en = v & (m.kind == wire.FINDNODE_RES)
            st = dataclasses.replace(st, lk=lk_mod.on_response(
                st.lk, dataclasses.replace(m, valid=en), metric_fn, lcfg))
            learned = m.nodes[:lcfg.frontier]
            l_ok = (learned != NO_NODE) & ctx.ready[jnp.maximum(learned, 0)]
            st = select_tree(
                en, self._routing_add(ctx, st, me_key, node_idx,
                                      jnp.where(l_ok, learned, NO_NODE),
                                      l_ok, now), st)

            # BucketCall server (handleBucketRequestRpc, Broose.cc:962-1008)
            en = v & (m.kind == wire.BROOSE_BUCKET_CALL) & (
                (st.state == BSET) | (st.state == READY))
            is_left = m.a == BT_LEFT
            lb_pad = jnp.concatenate(
                [st.lb, jnp.full((max(p.bb_size - p.lb_size, 0),), NO_NODE,
                                 I32)])[:p.bb_size]
            src_bucket = jnp.where(is_left, lb_pad, st.bb)
            nb_src = jnp.where(is_left,
                               jnp.sum((st.lb != NO_NODE).astype(I32)),
                               jnp.sum((st.bb != NO_NODE).astype(I32)))
            payload = jnp.full((rmax,), NO_NODE, I32)
            take = min(rmax, p.bb_size)
            payload = payload.at[:take].set(src_bucket[:take])
            payload = jnp.where(jnp.arange(rmax) < jnp.minimum(nb_src, rmax),
                                payload, NO_NODE)
            ob.send(en, now, m.src, wire.BROOSE_BUCKET_RES, a=m.a, b=m.b,
                    nodes=payload,
                    size_b=wire.BASE_CALL_B
                    + wire.NODEHANDLE_B * min(rmax, p.bb_size))

            # BucketResponse → join state machine
            # (handleBucketResponseRpc, Broose.cc:1010-1052)
            en = v & (m.kind == wire.BROOSE_BUCKET_RES)
            learned = m.nodes[:rmax]
            lb_ok = (learned[:lcfg.frontier] != NO_NODE) \
                & ctx.ready[jnp.maximum(learned[:lcfg.frontier], 0)]
            st = select_tree(
                en, self._routing_add(
                    ctx, st, me_key, node_idx,
                    jnp.where(lb_ok, learned[:lcfg.frontier], NO_NODE),
                    lb_ok, now), st)
            # INIT: BROTHER/PINIT responses
            hit_i = en & (st.state == INIT) & (m.b == PR_INIT)
            jb = st.jb_recv + hit_i.astype(I32)
            to_rset = hit_i & (jb >= p.pow_shift)
            # RSET: LEFT/PRSET responses
            hit_r = en & (st.state == RSET) & (m.b == PR_RSET)
            pr = st.pr_recv + hit_r.astype(I32)
            to_bset = hit_r & (pr >= st.pr_need)
            # BSET: LEFT/PBSET responses
            hit_b = en & (st.state == BSET) & (m.b == PR_BSET)
            pb = st.pb_recv + hit_b.astype(I32)
            to_ready = hit_b & (pb >= st.pb_need)
            # state-entry bookkeeping
            rb_flat = st.rb.reshape(-1)
            n_rb = jnp.sum(((rb_flat != NO_NODE)
                            & ~K.dup_mask(rb_flat)).astype(I32))
            n_bb = jnp.sum((st.bb != NO_NODE).astype(I32))
            st = dataclasses.replace(
                st,
                jb_recv=jb,
                pr_recv=jnp.where(to_rset, 0, pr),
                pb_recv=jnp.where(to_bset, 0, pb),
                state=jnp.where(to_rset, RSET,
                                jnp.where(to_bset, BSET, st.state)),
                pr_need=jnp.where(to_rset, (n_rb + 1) // 2,
                                  st.pr_need).astype(I32),
                pr_cursor=jnp.where(to_rset, 0, st.pr_cursor),
                pb_need=jnp.where(to_bset, (n_bb + 1) // 2,
                                  st.pb_need).astype(I32),
                pb_cursor=jnp.where(to_bset, 0, st.pb_cursor),
                t_join=jnp.where(to_rset | to_bset, now, st.t_join),
                state_to=jnp.where(to_rset | to_bset, now + state_to_ns,
                                   st.state_to))
            joins_cnt += to_ready.astype(I32)
            st = self._become_ready(ctx, st, to_ready, now, rngs[0])

            # app-owned kinds
            sib_app = self._is_sibling(ctx, st, me_key, m.key)
            st = dataclasses.replace(st, app=self.app.on_msg(
                st.app, m, ctx, ob, ev, sib_app))

            # pings (refresh liveness)
            ob.send(v & (m.kind == wire.PING_CALL), now, m.src,
                    wire.PING_RES, a=m.a, size_b=wire.BASE_CALL_B)
            en = v & (m.kind == wire.PING_RES)
            phit = en & (st.ping_dst == m.src)
            st = dataclasses.replace(
                st,
                ping_dst=jnp.where(phit, NO_NODE, st.ping_dst),
                ping_to=jnp.where(phit, T_INF, st.ping_to))

        # ------------------------------------------------------- timers ----
        # join timer in INIT (handleJoinTimerExpired, Broose.cc:268-318):
        # 2^s lookups for i·2^(B-s) + (me >> s), seeded at the bootstrap
        en_j = (st.state == INIT) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        no_jb = ~jnp.any(st.lk.active & (st.lk.purpose == P_JOINB))
        alone = en_j & (boot == NO_NODE)
        joins_cnt += alone.astype(I32)
        st = self._become_ready(ctx, st, alone, now_j, rngs[2])
        fire_j = en_j & ~alone & no_jb & (
            lk_mod.num_free(st.lk) >= p.pow_shift)
        shr_me = K.shr_const(me_key, s, spec)
        for i in range(p.pow_shift):
            tgt_key = K.add(shr_me, K.from_int(i << (spec.bits - s), spec),
                            spec)
            slot, have = lk_mod.free_slot(st.lk)
            seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(boot)
            ext0 = self._pack_ext(jnp.zeros((spec.lanes,), U32),
                                  jnp.int32(0), jnp.int32(0), node_idx)
            st = dataclasses.replace(st, lk=lk_mod.start(
                st.lk, fire_j & have, slot, P_JOINB, i, tgt_key, seed,
                now_j, lcfg, ext=ext0))
        st = dataclasses.replace(
            st,
            t_join=jnp.where(en_j & ~alone,
                             now_j + jnp.int64(int(p.join_delay * NS)),
                             st.t_join),
            state_to=jnp.where(fire_j, now_j + state_to_ns, st.state_to),
            jb_recv=jnp.where(fire_j, 0, st.jb_recv))

        # pacing timer in RSET/BSET: next batch of LBucket calls
        en_p = (st.state == RSET) & (st.t_join < t_end)
        now_p = jnp.maximum(st.t_join, t0)
        cur = self._paced_calls(st, ob, en_p, now_p, st.rb.reshape(-1),
                                st.pr_cursor, PR_RSET)
        more = cur > st.pr_cursor
        st = dataclasses.replace(
            st, pr_cursor=cur,
            t_join=jnp.where(en_p, jnp.where(more, now_p + pace_ns, T_INF),
                             st.t_join))
        en_p = (st.state == BSET) & (st.t_join < t_end)
        now_p = jnp.maximum(st.t_join, t0)
        cur = self._paced_calls(st, ob, en_p, now_p, st.bb, st.pb_cursor,
                                PR_BSET)
        more = cur > st.pb_cursor
        st = dataclasses.replace(
            st, pb_cursor=cur,
            t_join=jnp.where(en_p, jnp.where(more, now_p + pace_ns, T_INF),
                             st.t_join))

        # join-state deadline → restart from INIT (module docstring)
        en_d = (st.state >= INIT) & (st.state < READY) & (
            st.state_to < t_end)
        retries_cnt += en_d.astype(I32)
        st = self._restart_join_node(st, en_d, jnp.maximum(st.state_to, t0),
                                     rngs[3])

        # refresh timer (handleBucketTimerExpired, Broose.cc:318-341):
        # ping the stalest entries; bounded concurrent pings
        en_b = (st.state == READY) & (st.t_bucket < t_end)
        now_b = jnp.maximum(st.t_bucket, t0)
        refresh_ns = jnp.int64(int(p.refresh_time * NS))
        all_e = jnp.concatenate([st.rb.reshape(-1), st.lb, st.bb])
        all_seen = jnp.concatenate([st.rb_seen.reshape(-1), st.lb_seen,
                                    st.bb_seen])
        stale = (all_e != NO_NODE) & ~K.dup_mask(all_e) & (
            all_seen + refresh_ns < now_b)
        order = jnp.argsort(jnp.where(stale, all_seen, T_INF))  # analysis: allow(sort-call)
        for j in range(p.ping_slots):
            free = st.ping_dst[j] == NO_NODE
            tgt = all_e[order[j]]
            fire = en_b & free & stale[order[j]]
            ob.send(fire, now_b, tgt, wire.PING_CALL,
                    size_b=wire.BASE_CALL_B)
            st = dataclasses.replace(
                st,
                ping_dst=st.ping_dst.at[j].set(
                    jnp.where(fire, tgt, st.ping_dst[j])),
                ping_to=st.ping_to.at[j].set(
                    jnp.where(fire, now_b + jnp.int64(
                        int(p.rpc_timeout * NS)), st.ping_to[j])))
        # periodic brother-bucket exchange: pull a random brother's B
        # bucket so the sibling set keeps converging (the reference
        # refreshes via its continuous BucketCall traffic; with learns
        # READY-gated an explicit pull keeps bb complete)
        nbb = jnp.sum((st.bb != NO_NODE).astype(I32))
        pick = jax.random.randint(rngs[7], (), 0, jnp.maximum(nbb, 1),
                                  dtype=I32)
        btgt = st.bb[jnp.clip(pick, 0, p.bb_size - 1)]
        ob.send(en_b & (btgt != NO_NODE), now_b, btgt,
                wire.BROOSE_BUCKET_CALL, a=jnp.int32(BT_BROTHER),
                b=jnp.int32(PR_REFRESH), size_b=wire.BASE_CALL_B + 2)
        st = dataclasses.replace(st, t_bucket=jnp.where(
            en_b, now_b + refresh_ns // 2, st.t_bucket))

        # ping timeouts → remove from all buckets
        pto = st.ping_to < t_end
        ping_failed = jnp.where(pto, st.ping_dst, NO_NODE)
        st = dataclasses.replace(
            st,
            ping_dst=jnp.where(pto, NO_NODE, st.ping_dst),
            ping_to=jnp.where(pto, T_INF, st.ping_to))
        st = self._handle_failed(ctx, st, me_key, node_idx, ping_failed)

        # app timer
        # graceful-leave: hand app data to the closest brother and stop
        # firing app tests during the grace window (apps/base.py on_leave)
        st = dataclasses.replace(st, app=app_base.leave_protocol(
            self.app, st.app, ctx, ob, ev, t0, node_idx, st.bb[0],
            st.state == READY))
        en_a = (st.state == READY) & (
            self.app.next_event(st.app) < t_end)
        now_a = jnp.maximum(self.app.next_event(st.app), t0)
        app, req = self.app.on_timer(st.app, en_a, ctx, now_a, rngs[4], ev, node_idx)
        st = dataclasses.replace(st, app=app)
        ext_a = self._pack_ext(jnp.zeros((spec.lanes,), U32), jnp.int32(0),
                               jnp.int32(0), NO_NODE)
        seed_a, sib_a, ext_a, _, _ = self._eval_find(
            ctx, st, me_key, node_idx, req.key, ext_a, rmax)
        st = dataclasses.replace(
            st, choose=st.choose + (req.want & ~sib_a).astype(I32))
        local = req.want & sib_a
        res_local = seed_a[:lcfg.frontier]
        slot, have = lk_mod.free_slot(st.lk)
        if self.rcfg is not None and hasattr(self.app, "route_policy"):
            # routable payloads leave recursively, seeded with the
            # origination eval's initialized ext
            new_rr, new_app, route_fire, start_app = rt_mod.originate(
                st.rr, ob, self.app, st.app, req, seed_a[0], sib_a, have,
                now_a, node_idx, rmax, self.rcfg, ctx.measuring,
                ext0=ext_a)
            st = dataclasses.replace(st, rr=new_rr, app=new_app)
        else:
            route_fire = jnp.bool_(False)
            start_app = req.want & ~sib_a & have & (seed_a[0] != NO_NODE)
        insta_fail = req.want & ~sib_a & ~start_app & ~route_fire
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=local | insta_fail, success=local, tag=req.tag,
                target=req.key,
                results=jnp.where(local, res_local, NO_NODE),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_app, slot, P_APP, req.tag, req.key,
            seed_a[:lcfg.frontier], now_a, lcfg, ext=ext_a))

        # ------------------------------------------------ lookup timeouts --
        new_lk, failed_nodes, _ = lk_mod.on_timeouts(st.lk, t_end, t0, lcfg)
        st = dataclasses.replace(st, lk=new_lk)
        st = self._handle_failed(ctx, st, me_key, node_idx, failed_nodes)

        # route-hop ACK timeouts → bucket removal + reroute.  The new
        # next hop comes from re-running the shift-routing eval over the
        # parked key + parked ext; the RE-SENT message still carries the
        # PARKED ext (reforward_batch resends rt.visited verbatim) — the
        # receiving hop advances it as usual
        if self.rcfg is not None:
            new_rr, rt_failed, rt_retry = rt_mod.on_timeouts(
                st.rr, t_end, self.rcfg)
            st = dataclasses.replace(st, rr=new_rr)
            st = self._handle_failed(ctx, st, me_key, node_idx, rt_failed)

            def _reroute_find(kk, vv):
                res, sib, _ext, ok, _ = self._eval_find(
                    ctx, st, me_key, node_idx, kk, vv[:ew], rmax)
                return res, sib
            res_q, sib_q = jax.vmap(_reroute_find)(st.rr.key,
                                                   st.rr.visited)
            new_rr, drop_q = rt_mod.reroute(
                st.rr, ob, res_q, sib_q, rt_failed, rt_retry, t0,
                node_idx, self.rcfg)
            st = dataclasses.replace(st, rr=new_rr)
            routedrop_cnt += drop_q

        # ------------------------------------------------- completions -----
        new_lk, comp = lk_mod.take_completions(st.lk, t_end)
        st = dataclasses.replace(st, lk=new_lk)
        comp_hops_ev = (comp["hops"].astype(jnp.float32),
                        comp["taken"] & comp["success"])
        for li in range(lcfg.slots):
            en = comp["taken"][li]
            suc = comp["success"][li] & (comp["result"][li] != NO_NODE)
            res = comp["result"][li]
            pur = comp["purpose"][li]
            lksucc_cnt += (en & suc).astype(I32)
            anyfail_cnt += (en & ~suc).astype(I32)

            # join bucket lookup → BBucketCall to the responsible node
            enj = en & (pur == P_JOINB) & (st.state == INIT)
            ob.send(enj & suc, t0, res, wire.BROOSE_BUCKET_CALL,
                    a=jnp.int32(BT_BROTHER), b=jnp.int32(PR_INIT),
                    size_b=wire.BASE_CALL_B + 2)
            # a failed join lookup restarts the join (reference: restart
            # on BucketCall timeout, Broose.cc:1055-1062)
            fail_j = enj & ~suc
            retries_cnt += fail_j.astype(I32)
            st = self._restart_join_node(st, fail_j, t0, rngs[5])

            # app lookup → app completion hook
            ena = en & (pur == P_APP)
            st = dataclasses.replace(st, app=self.app.on_lookup_done(
                st.app, app_base.LookupDone(
                    en=ena, success=ena & suc, tag=comp["aux"][li],
                    target=comp["target"][li], results=comp["results"][li],
                    hops=comp["hops"][li], t0=comp["t0"][li]),
                ctx, ob, ev, t0, node_idx))

        # ------------------------------------------------------- pump ------
        new_lk, _ = lk_mod.pump(st.lk, ob, ctx, node_idx, t0, rngs[6], lcfg)
        st = dataclasses.replace(st, lk=new_lk)

        # ------------------------------------------------------ events -----
        events = {
            "c:broose_joins": joins_cnt,
            "c:broose_join_retries": retries_cnt,
            "c:lookup_success": lksucc_cnt,
            "c:lookup_failed": anyfail_cnt,
            "c:route_dropped": routedrop_cnt,
            "s:lookup_hops": comp_hops_ev,
        }
        ev.finish(events, self.app.hist_map)
        return st, ob, events
