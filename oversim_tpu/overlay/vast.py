"""Vast — spatial AOI overlay (VON) for games, vectorized.

TPU-native rebuild of the reference Vast (src/overlay/vast/Vast.{h,cc}:
Voronoi-diagram neighbor discovery with AOI radius — Sites map,
buildVoronoi Vast.h:98; join via a greedy point query through existing
neighbors; move/event multicast to AOI neighbors; enclosing-neighbor
maintenance) driving the SimpleGameClient movement workload
(apps/movement.py generators).

Engine mapping (no KBR — spatial neighbor logic like GIA's degree
logic):

  * positions travel ON THE WIRE (2×f32 bitcast into the key field, the
    ncs piggyback pattern) — no oracle position reads in the protocol;
  * **join** (Vast::handleJoin): a JOIN carrying the joiner's position
    greedy-forwards to the neighbor closest to that position until no
    neighbor is closer than the current node (the reference's point
    query through the Voronoi), which ACKs with its neighbor list; the
    joiner HELLOs the listed nodes to exchange positions;
  * **move** (Vast::handleMove): every ``move_interval`` the position
    advances (movement generator) and a MOVE multicasts to the current
    neighbor set; receivers update the mover's stored position, drop it
    when it leaves the AOI (+hysteresis), and occasionally reply with a
    HINT listing their own neighbors nearest to the mover — the engine
    stand-in for enclosing-neighbor discovery (documented deviation: the
    true Voronoi cell construction is replaced by nearest-K + AOI-disc
    membership with hint gossip; the published VON behavior without
    per-node Voronoi tessellation);
  * neighbors are soft state pruned on silence (``nbr_timeout``).

Stats: joins, moves, position-update deliveries, neighbor count, and
the mean position error neighbors hold for each node (the game-overlay
consistency KPI the reference measures via ConnectivityProbeApp/GlobalCoordinator).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import movement as move_mod
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
F32 = jnp.float32
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

DEAD, JOINING, READY = 0, 1, 2

# wire kinds (spatial family: 110+)
V_JOIN = 110        # key=joiner pos, a=joiner slot, hops=greedy hops
V_JOIN_ACK = 111    # key=acceptor pos, nodes=its neighbors
V_MOVE = 112        # key=new pos
V_HINT = 113        # nodes=neighbors near the target
V_HELLO = 114       # key=pos, a=1 → ack requested
V_BYE = 115         # graceful neighbor removal


@dataclasses.dataclass(frozen=True)
class VastParams:
    aoi: float = 100.0            # AOIWidth (Vast.ned)
    max_nbr: int = 8              # neighbor set bound (D)
    move_interval: float = 5.0
    join_delay: float = 10.0
    nbr_timeout: float = 30.0     # soft-state prune
    hint_prob: float = 0.25       # HINT reply probability per MOVE
    join_ttl: int = 16            # greedy-forward bound
    move: move_mod.MoveParams = move_mod.MoveParams(
        field=300.0, speed=5.0)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class VastState:
    state: jnp.ndarray     # [N] i32
    pos: jnp.ndarray       # [N, 2] f32
    wp: jnp.ndarray        # [N, 2] f32
    nbr: jnp.ndarray       # [N, D] i32
    nbr_pos: jnp.ndarray   # [N, D, 2] f32
    nbr_seen: jnp.ndarray  # [N, D] i64
    t_join: jnp.ndarray    # [N] i64
    t_move: jnp.ndarray    # [N] i64
    t_prune: jnp.ndarray   # [N] i64
    seq: jnp.ndarray       # [N] i32


def _pack_pos(pos, lanes: int):
    words = jax.lax.bitcast_convert_type(pos.astype(F32), U32)
    return jnp.zeros((lanes,), U32).at[:2].set(words)


def _unpack_pos(key):
    return jax.lax.bitcast_convert_type(key[:2], F32)


class VastLogic:
    """Engine logic interface (engine/logic.py docstring)."""

    PREFIX = "vast"    # stat prefix (subclasses: quon)

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: VastParams = VastParams()):
        self.key_spec = spec
        self.p = params

    def stat_spec(self) -> stats_mod.StatSpec:
        x = self.PREFIX
        return stats_mod.StatSpec(
            scalars=(f"{x}_nbr_count", f"{x}_pos_err"),
            hists=(),
            counters=(f"{x}_joins", f"{x}_moves", f"{x}_updates",
                      f"{x}_hints", f"{x}_join_fwd"))

    def init(self, rng, n: int) -> VastState:
        d = self.p.max_nbr
        pos, wp = move_mod.init_positions(rng, n, self.p.move)
        return VastState(
            state=jnp.zeros((n,), I32),
            pos=pos, wp=wp,
            nbr=jnp.full((n, d), NO_NODE, I32),
            nbr_pos=jnp.zeros((n, d, 2), F32),
            nbr_seen=jnp.zeros((n, d), I64),
            t_join=jnp.full((n,), T_INF, I64),
            t_move=jnp.full((n,), T_INF, I64),
            t_prune=jnp.full((n,), T_INF, I64),
            seq=jnp.zeros((n,), I32))

    def split(self, st):
        return st, None

    def merge(self, node_part, glob):
        return node_part

    def post_step(self, ctx, st, events):
        return st

    def reset(self, st: VastState, clear, join, t_now, rng):
        n = st.state.shape[0]
        r_i, r_j = jax.random.split(rng)
        fresh = self.init(r_i, n)
        st = select_tree(clear, fresh, st)
        jitter = (jax.random.uniform(r_j, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: VastState):
        return st.state == READY

    def next_event(self, st: VastState):
        joining = st.state == JOINING
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        t = jnp.minimum(t, jnp.where(ready, st.t_move, T_INF))
        t = jnp.minimum(t, jnp.where(ready, st.t_prune, T_INF))
        return t

    # -- neighbor set ---------------------------------------------------------

    def _nbr_put(self, st, cands, cand_pos, now, me_pos, node_idx):
        """Merge candidates into the nearest-D neighbor set (the engine's
        stand-in for the Voronoi site set: nearest-K ∪ AOI disc)."""
        d = self.p.max_nbr
        cands = jnp.where(cands == node_idx, NO_NODE, cands)
        aug = jnp.concatenate([st.nbr, cands])
        augp = jnp.concatenate([st.nbr_pos, cand_pos])
        augs = jnp.concatenate([st.nbr_seen,
                                jnp.where(cands != NO_NODE, now, 0)])
        # duplicates: a re-announced neighbor refreshes pos + seen —
        # candidates override existing entries (candidates come later,
        # keep LAST occurrence by invalidating earlier dups)
        rev = aug[::-1]
        dup_rev = K.dup_mask(rev)
        dup = dup_rev[::-1]
        aug = jnp.where(dup, NO_NODE, aug)
        dist = jnp.sqrt(jnp.sum((augp - me_pos[None, :]) ** 2, axis=-1))
        dist = jnp.where(aug == NO_NODE, jnp.float32(1e30), dist)
        order = jnp.argsort(dist)  # analysis: allow(sort-call)
        aug, augp, augs = aug[order], augp[order], augs[order]
        return dataclasses.replace(
            st, nbr=aug[:d], nbr_pos=augp[:d], nbr_seen=augs[:d])

    def _nbr_drop(self, st, bad):
        hit = (st.nbr[:, None] == jnp.atleast_1d(bad)[None, :]).any(-1) & (
            st.nbr != NO_NODE)
        return dataclasses.replace(
            st,
            nbr=jnp.where(hit, NO_NODE, st.nbr),
            nbr_seen=jnp.where(hit, 0, st.nbr_seen))

    def _closest_to(self, st, target_pos):
        """(neighbor slot closest to target, its distance)."""
        dist = jnp.sqrt(jnp.sum(
            (st.nbr_pos - target_pos[None, :]) ** 2, axis=-1))
        dist = jnp.where(st.nbr == NO_NODE, jnp.float32(1e30), dist)
        j = jnp.argmin(dist)
        return st.nbr[j], dist[j]

    # -- the per-node step ----------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, spec = self.p, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        rngs = jax.random.split(rng, 6)
        t0 = ctx.t_start
        t_end = ctx.t_end
        d = p.max_nbr

        joins_cnt = jnp.int32(0)
        moves_cnt = jnp.int32(0)
        upd_cnt = jnp.int32(0)
        hint_cnt = jnp.int32(0)
        fwd_cnt = jnp.int32(0)

        def pad_nodes(vec):
            out = jnp.full((rmax,), NO_NODE, I32)
            k = min(vec.shape[0], rmax)
            return out.at[:k].set(vec[:k])

        # ------------------------------------------------------- inbox -----
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid
            mpos = _unpack_pos(m.key)

            # JOIN: greedy point query (Vast::handleJoinRequest).  Forward
            # to the neighbor closest to the joiner unless we are closest.
            en = v & (m.kind == V_JOIN) & (st.state == READY)
            cn, cd = self._closest_to(st, mpos)
            my_d = jnp.sqrt(jnp.sum((st.pos - mpos) ** 2))
            fwd = en & (cn != NO_NODE) & (cd < my_d) & (m.hops < p.join_ttl) \
                & (cn != m.a)
            ob.send(fwd, now, cn, V_JOIN, key=m.key, a=m.a,
                    hops=m.hops + 1, size_b=24)
            fwd_cnt += fwd.astype(I32)
            acc = en & ~fwd
            ob.send(acc, now, m.a, V_JOIN_ACK,
                    key=_pack_pos(st.pos, spec.lanes),
                    nodes=pad_nodes(st.nbr), size_b=24 + 6 * d)
            # the acceptor adopts the joiner too
            st = select_tree(acc, self._nbr_put(
                st, m.a[None], mpos[None], now, st.pos, node_idx), st)

            # JOIN_ACK: adopt the acceptor; HELLO its neighbors
            en = v & (m.kind == V_JOIN_ACK) & (st.state == JOINING)
            st = select_tree(en, self._nbr_put(
                st, m.src[None], mpos[None], now, st.pos, node_idx), st)
            for j in range(d):
                cand = m.nodes[j]
                ob.send(en & (cand != NO_NODE) & (cand != node_idx), now,
                        jnp.maximum(cand, 0), V_HELLO,
                        key=_pack_pos(st.pos, spec.lanes), a=jnp.int32(1),
                        size_b=24)
            joins_cnt += en.astype(I32)
            st = dataclasses.replace(
                st,
                state=jnp.where(en, READY, st.state),
                t_join=jnp.where(en, T_INF, st.t_join),
                t_move=jnp.where(en, now + jnp.int64(
                    int(p.move_interval * NS)), st.t_move),
                t_prune=jnp.where(en, now + jnp.int64(
                    int(p.nbr_timeout / 2 * NS)), st.t_prune))

            # HELLO: position exchange; adopt if near
            en = v & (m.kind == V_HELLO) & (st.state == READY)
            st = select_tree(en, self._nbr_put(
                st, m.src[None], mpos[None], now, st.pos, node_idx), st)
            ob.send(en & (m.a != 0), now, m.src, V_HELLO,
                    key=_pack_pos(st.pos, spec.lanes), a=jnp.int32(0),
                    size_b=24)

            # MOVE: refresh the mover; drop if it left the AOI (+50%
            # hysteresis); occasionally HINT our nearest neighbors
            en = v & (m.kind == V_MOVE) & (st.state == READY)
            dist_m = jnp.sqrt(jnp.sum((st.pos - mpos) ** 2))
            keep = en & (dist_m <= 1.5 * p.aoi)
            st = select_tree(keep, self._nbr_put(
                st, m.src[None], mpos[None], now, st.pos, node_idx), st)
            st = select_tree(en & ~keep, self._nbr_drop(st, m.src), st)
            upd_cnt += keep.astype(I32)
            do_hint = keep & (jax.random.uniform(
                jax.random.fold_in(rngs[4], r), ()) < p.hint_prob)
            # neighbors nearest to the MOVER (enclosing-discovery hint)
            hd = jnp.sqrt(jnp.sum((st.nbr_pos - mpos[None, :]) ** 2,
                                  axis=-1))
            hd = jnp.where((st.nbr == NO_NODE) | (st.nbr == m.src),
                           jnp.float32(1e30), hd)
            order = jnp.argsort(hd)  # analysis: allow(sort-call)
            hint_nodes = jnp.where(hd[order] < p.aoi, st.nbr[order],
                                   NO_NODE)[:4]
            ob.send(do_hint & jnp.any(hint_nodes != NO_NODE), now, m.src,
                    V_HINT, nodes=pad_nodes(hint_nodes), size_b=6 * 4)
            hint_cnt += do_hint.astype(I32)

            # HINT: HELLO unknown hinted nodes
            en = v & (m.kind == V_HINT) & (st.state == READY)
            for j in range(4):
                cand = m.nodes[j]
                known = jnp.any(st.nbr == cand)
                ob.send(en & (cand != NO_NODE) & (cand != node_idx)
                        & ~known, now, jnp.maximum(cand, 0), V_HELLO,
                        key=_pack_pos(st.pos, spec.lanes), a=jnp.int32(1),
                        size_b=24)

            # BYE: graceful removal
            en = v & (m.kind == V_BYE)
            st = select_tree(en, self._nbr_drop(st, m.src), st)

        # ------------------------------------------------------- timers ----
        # join (greedy point query seeded at a bootstrap node)
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        alone = en_j & (boot == NO_NODE)
        joins_cnt += alone.astype(I32)
        st = dataclasses.replace(
            st,
            state=jnp.where(alone, READY, st.state),
            t_move=jnp.where(alone, now_j + jnp.int64(
                int(p.move_interval * NS)), st.t_move),
            t_prune=jnp.where(alone, now_j + jnp.int64(
                int(p.nbr_timeout / 2 * NS)), st.t_prune),
            t_join=jnp.where(
                alone, T_INF,
                jnp.where(en_j, now_j + jnp.int64(
                    int(p.join_delay * NS)), st.t_join)))
        ob.send(en_j & ~alone, now_j, jnp.maximum(boot, 0), V_JOIN,
                key=_pack_pos(st.pos, spec.lanes), a=node_idx,
                hops=jnp.int32(0), size_b=24)

        # move + update multicast (Vast::handleMove + movement generator)
        en_m = (st.state == READY) & (st.t_move < t_end) \
            & ~ctx.leaving[node_idx]
        now_m = jnp.maximum(st.t_move, t0)
        new_pos, new_wp = move_mod.step(st.pos, st.wp,
                                        jnp.float32(p.move_interval),
                                        rngs[2], p.move,
                                        t_s=t0.astype(jnp.float32) / NS)
        st = dataclasses.replace(
            st,
            pos=jnp.where(en_m, new_pos, st.pos),
            wp=jnp.where(en_m, new_wp, st.wp),
            t_move=jnp.where((st.state == READY) & (st.t_move < t_end),
                             now_m + jnp.int64(int(p.move_interval * NS)),
                             st.t_move))
        moves_cnt += en_m.astype(I32)
        for j in range(d):
            tgt = st.nbr[j]
            ob.send(en_m & (tgt != NO_NODE), now_m, jnp.maximum(tgt, 0),
                    V_MOVE, key=_pack_pos(st.pos, spec.lanes), size_b=24)

        # prune silent neighbors (soft state)
        en_p = (st.state == READY) & (st.t_prune < t_end)
        now_p = jnp.maximum(st.t_prune, t0)
        stale = (st.nbr != NO_NODE) & (
            st.nbr_seen + jnp.int64(int(p.nbr_timeout * NS)) < now_p)
        st = dataclasses.replace(
            st,
            nbr=jnp.where(en_p & stale, NO_NODE, st.nbr),
            nbr_seen=jnp.where(en_p & stale, 0, st.nbr_seen),
            t_prune=jnp.where(en_p, now_p + jnp.int64(
                int(p.nbr_timeout / 2 * NS)), st.t_prune))
        # a READY node with no neighbors rejoins (lost the overlay)
        lost = (st.state == READY) & en_p & ~jnp.any(st.nbr != NO_NODE) \
            & (ctx.n_ready > 1)
        st = dataclasses.replace(
            st,
            state=jnp.where(lost, JOINING, st.state),
            t_join=jnp.where(lost, now_p, st.t_join),
            t_move=jnp.where(lost, T_INF, st.t_move),
            t_prune=jnp.where(lost, T_INF, st.t_prune))

        # ------------------------------------------------------ events -----
        nbr_n = jnp.sum((st.nbr != NO_NODE).astype(I32))
        x = self.PREFIX
        events = {
            f"c:{x}_joins": joins_cnt,
            f"c:{x}_moves": moves_cnt,
            f"c:{x}_updates": upd_cnt,
            f"c:{x}_hints": hint_cnt,
            f"c:{x}_join_fwd": fwd_cnt,
            f"s:{x}_nbr_count": (nbr_n.astype(F32)[None],
                                 (st.state == READY)[None]),
            f"s:{x}_pos_err": (jnp.zeros((1,), F32), jnp.zeros((1,), bool)),
        }
        return st, ob, events
