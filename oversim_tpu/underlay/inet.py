"""Router-topology underlay (vectorized InetUnderlay + ReaSE).

TPU-native equivalent of the reference's InetUnderlay
(src/underlay/inetunderlay/: InetUnderlayConfigurator.cc creates
terminal hosts behind access routers — createNode → AccessNet::addOverlayNode
picks the access router and channel, AccessNet.cc:120-220 — on a router
backbone wired from NED topology templates) and of ReaSEUnderlay
(src/underlay/reaseunderlay/: the same stack on ReaSE-generated
realistic AS-level topologies with transit/stub hierarchy).

The reference routes real IPv4 packets hop by hop through INET's
network stack; end-to-end latency is the sum of link delays + per-link
serialization on the routed path.  The TPU rebuild precomputes exactly
that quantity once: a static router graph is built at init (host-side
numpy, like the OMNeT++ topology setup phase), all-pairs shortest-path
delays become a [R, R] matrix, and a message's propagation delay is one
gather:

    delay = access_delay[src] + rr_delay[router[src], router[dst]]
          + access_delay[dst] + tx serialization + rx serialization

Sender-side queue serialization, jitter, bit errors, dead-destination
and partition drops follow the same model as underlay/simple.py (the
reference shares that logic between underlays via SimpleUDP vs real
UDP gates).

Topologies:
  * "inet"  — flat random backbone: routers placed uniformly, each
    linked to its 2 nearest neighbors + a ring for connectivity (the
    reference's default inet topology templates are small handmade
    backbones, e.g. src/underlay/inetunderlay/topologies/).
  * "rease" — two-tier AS hierarchy: a densely meshed transit core and
    stub routers preferentially attached to the core (ReaSE's
    transit-stub TGM output), giving the fatter delay spread of
    realistic AS graphs.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oversim_tpu.underlay.simple import (CHANNELS, connection_matrix,
                                         node_types)

I32 = jnp.int32
I64 = jnp.int64
F32 = jnp.float32
NS = 1_000_000_000
T_MAX = jnp.int64(2**62)


@dataclasses.dataclass(frozen=True)
class InetUnderlayParams:
    """Static configuration (reference InetUnderlay.ned/ReaSEUnderlay.ned
    + omnetpp.ini accessRouterNum/overlayAccessRouterNum)."""

    topology: str = "inet"             # "inet" | "rease"
    routers: int = 16                  # backbone/access router count
    transit: int = 4                   # rease: transit-core size
    link_delay: float = 0.010          # per backbone link (s); INET ned
    access_delay_min: float = 0.001    # terminal↔access-router latency
    access_delay_max: float = 0.020
    jitter: float = 0.1
    send_queue_bytes: int = 1_000_000
    channel_types: tuple = ("simple_ethernetline",)
    header_bytes: int = 28
    # partition support (same semantics as underlay/simple.py)
    num_node_types: int = 1
    type_boundaries: tuple = ()
    partition_events: tuple = ()

    @property
    def channel_table(self):
        rows = [CHANNELS[c] for c in self.channel_types]
        return jnp.asarray(rows, dtype=F32)


def _apsp(adj: np.ndarray) -> np.ndarray:
    """All-pairs shortest path (Floyd–Warshall) over a delay matrix."""
    d = adj.copy()
    r = d.shape[0]
    for k in range(r):
        d = np.minimum(d, d[:, k:k + 1] + d[k:k + 1, :])
    return d


def build_topology(seed: int, p: InetUnderlayParams) -> np.ndarray:
    """[R, R] f32 router-to-router delay matrix (host-side, init only)."""
    r = p.routers
    rs = np.random.RandomState(seed)
    inf = 1e9
    adj = np.full((r, r), inf, np.float64)
    np.fill_diagonal(adj, 0.0)

    def link(i, j, mult=1.0):
        d = p.link_delay * mult
        adj[i, j] = min(adj[i, j], d)
        adj[j, i] = min(adj[j, i], d)

    if p.topology == "rease":
        t = min(p.transit, r)
        # transit core: full mesh with short links (AS core peering)
        for i in range(t):
            for j in range(i + 1, t):
                link(i, j, 0.5)
        # stubs: preferential attachment to the core + one stub peer
        for i in range(t, r):
            link(i, int(rs.randint(0, t)))
            if i > t:
                link(i, int(rs.randint(t, i)), 2.0)
    else:
        # flat backbone: ring for connectivity + 2-nearest-neighbor links
        pos = rs.uniform(0.0, 1.0, (r, 2))
        for i in range(r):
            link(i, (i + 1) % r)
        for i in range(r):
            d2 = np.sum((pos - pos[i]) ** 2, axis=1)
            d2[i] = np.inf
            for j in np.argsort(d2)[:2]:
                link(i, int(j))
    return _apsp(adj).astype(np.float32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class InetUnderlayState:
    router: jnp.ndarray       # [N] i32 access router per node
    access: jnp.ndarray       # [N] f32 terminal↔router delay (s)
    channel: jnp.ndarray      # [N] i32 index into channel_table
    tx_finished: jnp.ndarray  # [N] i64
    node_type: jnp.ndarray    # [N] i32
    rr_delay: jnp.ndarray     # [R, R] f32 backbone delay matrix


def init(rng: jax.Array, n: int, p: InetUnderlayParams) -> InetUnderlayState:
    rk, ak, ck, tk = jax.random.split(rng, 4)
    seed = int(jax.random.randint(tk, (), 0, 2**31 - 1))
    rr = jnp.asarray(build_topology(seed, p))
    return InetUnderlayState(
        router=jax.random.randint(rk, (n,), 0, p.routers, dtype=I32),
        access=jax.random.uniform(ak, (n,), F32, p.access_delay_min,
                                  p.access_delay_max),
        channel=jax.random.randint(ck, (n,), 0, len(p.channel_types),
                                   dtype=I32),
        tx_finished=jnp.zeros((n,), I64),
        node_type=node_types(n, p),
        rr_delay=rr)


def migrate(state: InetUnderlayState, mask, rng,
            p: InetUnderlayParams) -> InetUnderlayState:
    """Re-home created nodes on a fresh access router (the reference's
    InetUnderlayConfigurator::migrateNode re-runs addOverlayNode)."""
    n = state.router.shape[0]
    rk, ak = jax.random.split(rng)
    router = jnp.where(mask, jax.random.randint(rk, (n,), 0, p.routers,
                                                dtype=I32), state.router)
    access = jnp.where(mask, jax.random.uniform(
        ak, (n,), F32, p.access_delay_min, p.access_delay_max),
        state.access)
    tx_finished = jnp.where(mask, jnp.int64(0), state.tx_finished)
    return dataclasses.replace(state, router=router, access=access,
                               tx_finished=tx_finished)


@partial(jax.jit, static_argnames=("p",))
def send_batch(state: InetUnderlayState, p: InetUnderlayParams, rng,
               src, dst, size_bytes, t_send, want, alive, kind=None):
    """Same contract as underlay.simple.send_batch (the engine is
    underlay-agnostic): (t_deliver, ok, new_state, drops)."""
    n, m = src.shape
    tbl = p.channel_table
    bits = (size_bytes + p.header_bytes) * 8

    tx_bw = tbl[state.channel, 0][:, None]
    tx_ber = tbl[state.channel, 2][:, None]
    rx_bw = tbl[state.channel[dst], 0]
    rx_ber = tbl[state.channel[dst], 2]

    self_send = src == dst
    queued = want & ~self_send

    # sender queue serialization (shared model; simple.py:173-189)
    bw_delay_ns = jnp.where(queued,
                            (bits.astype(F32) / tx_bw * NS), 0.0).astype(I64)
    start0 = jnp.maximum(state.tx_finished[:, None], t_send)
    cum = jnp.cumsum(bw_delay_ns, axis=1)
    finish = start0 + cum
    max_queue_ns = (jnp.float32(p.send_queue_bytes * 8) / tx_bw * NS
                    ).astype(I64)
    overrun = queued & (finish - t_send > max_queue_ns)
    new_tx_finished = jnp.where(
        jnp.any(queued & ~overrun, axis=1),
        jnp.max(jnp.where(queued & ~overrun, finish, 0), axis=1),
        state.tx_finished)

    # routed-path propagation: access + backbone APSP + access
    backbone = state.rr_delay[state.router[:, None], state.router[dst]]
    prop = state.access[:, None] + backbone + state.access[dst]
    rx_delay = bits.astype(F32) / rx_bw
    total_ns = (finish - t_send) + ((prop + rx_delay) * NS).astype(I64)

    if p.jitter > 0:
        jit = jnp.abs(jax.random.normal(rng, (n, m), dtype=F32))
        total_ns = total_ns + (jit * p.jitter *
                               total_ns.astype(F32)).astype(I64)

    bit_err_p = 1.0 - (1.0 - tx_ber) ** bits * (1.0 - rx_ber) ** bits
    u = jax.random.uniform(jax.random.fold_in(rng, 1), (n, m), dtype=F32)
    bit_error = queued & (u < bit_err_p)
    dest_dead = want & ~alive[dst]

    if p.partition_events:
        conn = connection_matrix(p, jnp.min(jnp.where(want, t_send,
                                                      T_MAX)))
        part_cut = want & ~conn[state.node_type[src],
                                state.node_type[dst]]
    else:
        part_cut = jnp.zeros_like(want)

    ok = want & ~overrun & ~bit_error & ~dest_dead & ~part_cut
    t_deliver = jnp.where(self_send, t_send, t_send + total_ns)

    new_state = dataclasses.replace(state, tx_finished=new_tx_finished)
    drops = {
        "queue_lost": jnp.sum(overrun & want),
        "bit_error_lost": jnp.sum(bit_error),
        "dest_unavailable_lost": jnp.sum(dest_dead),
        "partition_lost": jnp.sum(part_cut),
    }
    return t_deliver, ok, new_state, drops


# strategy-module aliases (engine/sim.py resolves <module>.UnderlayParams)
UnderlayParams = InetUnderlayParams
UnderlayState = InetUnderlayState
