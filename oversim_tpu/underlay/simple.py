"""Analytic underlay network model (vectorized SimpleUnderlay).

TPU-native equivalent of the reference's SimpleUnderlay
(src/underlay/simpleunderlay/): no packet-level simulation — every node has
an N-dim coordinate and per-direction channel parameters, and the
end-to-end delay of a packet is computed analytically:

    delay = send-queue carry + tx bandwidth delay + tx access delay
          + 0.001 * euclidean(coords_src, coords_dst)
          + rx bandwidth delay + rx access delay
          (+ positive half-normal jitter with sigma = jitter * delay)

mirroring SimpleNodeEntry::calcDelay (SimpleNodeEntry.cc:155-195, the
0.001 s/coord-unit constant at :186) and SimpleUDP::processMsgFromApp
(SimpleUDP.cc:274-434: self-sends bypass the delay model, dest-unavailable
and partition drops, jitter workaround loop).  Drops: send-queue overrun
(calcDelay :169-180), bit errors from channel error rate, destination dead,
node-type partition (GlobalNodeList::areNodeTypesConnected).

All of it is computed for a whole ``[N, MOUT]`` outbox batch at once; the
per-sender transmit-queue serialization (``tx.finished`` carry) becomes a
cumulative sum along the outbox axis.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

I64 = jnp.int64
F32 = jnp.float32
NS = 1_000_000_000  # ns per second
T_MAX = jnp.int64(2**62)

# Channel catalogue (reference: src/common/channels.ned:3-34).
# columns: bandwidth bit/s, access delay s, bit error rate
CHANNELS = {
    "simple_ethernetline": (10e6, 0.0, 0.0),
    "simple_ethernetline_lossy": (10e6, 0.0, 1e-5),
    "simple_dsl": (1e6, 0.020, 0.0),
    "simple_dsl_lossy": (1e6, 0.020, 1e-5),
}


@dataclasses.dataclass(frozen=True)
class UnderlayParams:
    """Static SimpleUnderlay configuration (simulations/default.ini:545-563)."""

    dims: int = 2
    field_size: float = 150.0          # default.ini:552
    # node-coordinate XML pool (nodeCoordinateSource, default.ini:555:
    # PlanetLab-derived positions instead of uniform draws; parsed by
    # native/coordpool.c).  Empty = uniform random in the field.
    coord_source: str = ""
    coord_delay_per_unit: float = 0.001  # s per coord unit, SimpleNodeEntry.cc:186
    use_coordinate_based_delay: bool = True  # default.ini:547
    constant_delay: float = 0.050      # fallback, default.ini:545
    jitter: float = 0.1                # default.ini:549
    send_queue_bytes: int = 1_000_000  # default.ini:553 "1MB"
    channel_types: tuple = ("simple_ethernetline",)
    header_bytes: int = 28             # UDP(8) + IP(20), SimpleUDP.cc:291
    # --- node-type partitions (GlobalNodeList connectionMatrix,
    # GlobalNodeList.h:232-235 + SimpleUDP.cc:349-358 partition drop;
    # driven by CONNECT/DISCONNECT_NODETYPES trace events,
    # simulations/partition.trace) ---
    # --- PlanetLab delay-fault model (delayFaultType, SimpleUDP.cc:
    # 126-141; SimpleNodeEntry::getFaultyDelay :197-254): inject
    # triangle-inequality-violating delay errors with ratios drawn from
    # the Kumaraswamy fits of "Network Coordinates in the Wild" Fig. 7.
    # ""|"live_all"|"live_planetlab"|"simulation".  The error is a
    # DETERMINISTIC hash of the un-faulted delay (the reference hashes
    # the delay string) so a given pair distance always gets the same
    # distortion — stable violations, not jitter.
    delay_fault_type: str = ""
    # --- SimpleTCP / BaseTcpSupport (src/underlay/simpleunderlay/
    # SimpleTCP.{h,cc}, src/common/BaseTcpSupport.{h,cc}): message kinds
    # listed here ride a simulated TCP stream to their destination —
    # reliable (a bit error retransmits, adding one RTO-scaled delay,
    # instead of dropping) and connection-oriented (first contact with a
    # peer outside the open-connection cache pays a SYN/SYN-ACK/ACK
    # handshake of 1.5 one-way delays, ExtTCPSocketMap connection
    # reuse).  Empty = everything is UDP, zero state/graph cost.
    tcp_kinds: tuple = ()
    tcp_connection_cache: int = 8     # open connections kept per node
    num_node_types: int = 1
    # slots < type_boundaries[0] are type 0, < [1] type 1, ...; the last
    # type takes the rest (multiple ChurnGenerators = one type each,
    # ChurnGenerator.h:42-50)
    type_boundaries: tuple = ()
    # static schedule: (time_s, type_a, type_b, connect) — applied in
    # order; the matrix starts fully connected
    partition_events: tuple = ()

    @property
    def channel_table(self):
        """[C, 3] float32 table of (bandwidth, access_delay, ber)."""
        rows = [CHANNELS[c] for c in self.channel_types]
        return jnp.asarray(rows, dtype=F32)


def node_types(n: int, p: UnderlayParams) -> jnp.ndarray:
    """[N] i32 node type per slot from the static boundaries."""
    t = jnp.zeros((n,), jnp.int32)
    for b in p.type_boundaries:
        t = t + (jnp.arange(n) >= b).astype(jnp.int32)
    return jnp.clip(t, 0, p.num_node_types - 1)


def connection_matrix(p: UnderlayParams, t_now) -> jnp.ndarray:
    """[T, T] bool connectivity at simulated time ``t_now`` (ns scalar),
    replayed from the static partition schedule each tick (the reference
    mutates GlobalNodeList::connectionMatrix via trace commands).

    Events are ONE-directional like the reference's connect/
    disconnectNodeTypes (GlobalNodeList.cc; simulations/partition.trace
    issues both directions explicitly) — a full split needs (a,b) and
    (b,a) events."""
    t = p.num_node_types
    conn = jnp.ones((t, t), bool)
    for (ts, a, b, connect) in p.partition_events:
        en = jnp.int64(int(ts * NS)) <= t_now
        conn = conn.at[a, b].set(jnp.where(en, bool(connect), conn[a, b]))
    return conn


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class UnderlayState:
    """Per-node underlay state, all arrays [N, ...]."""

    coords: jnp.ndarray       # [N, D] f32
    channel: jnp.ndarray      # [N] i32 index into channel_table
    tx_finished: jnp.ndarray  # [N] i64 ns — when the send queue drains
    node_type: jnp.ndarray    # [N] i32 — churn-generator/partition type
    tcp_conn: jnp.ndarray     # [N, Ct] i32 — open-connection peer cache
                              # (SimpleTCP/BaseTcpSupport, zero-width
                              # when no tcp_kinds are configured)


_POOL_CACHE: dict = {}


def _coord_pool(p: UnderlayParams):
    """[P, D] device constant from the XML pool (trace-time cached)."""
    if p.coord_source not in _POOL_CACHE:
        from oversim_tpu import native as native_mod
        arr = native_mod.load_coord_pool(p.coord_source)
        _POOL_CACHE[p.coord_source] = jnp.asarray(
            arr[:, :p.dims], dtype=F32)
    return _POOL_CACHE[p.coord_source]


def _draw_coords(rng, n: int, p: UnderlayParams):
    if p.coord_source:
        pool = _coord_pool(p)
        idx = jax.random.randint(rng, (n,), 0, pool.shape[0])
        return pool[idx]
    return jax.random.uniform(
        rng, (n, p.dims), dtype=F32, minval=0.0, maxval=p.field_size)


def init(rng: jax.Array, n: int, p: UnderlayParams) -> UnderlayState:
    """Coordinates from the XML pool (or uniform in the field), random
    channel type per node (reference: SimpleUnderlayConfigurator.cc:143-184
    draws coords from the pool and the channel type uniformly from
    churnGenerator channelTypes)."""
    ck, xk = jax.random.split(rng)
    coords = _draw_coords(xk, n, p)
    channel = jax.random.randint(ck, (n,), 0, len(p.channel_types), dtype=jnp.int32)
    ct = p.tcp_connection_cache if p.tcp_kinds else 0
    return UnderlayState(coords=coords, channel=channel,
                         tx_finished=jnp.zeros((n,), dtype=I64),
                         node_type=node_types(n, p),
                         tcp_conn=jnp.full((n, ct), -1, jnp.int32))


def migrate(state: UnderlayState, mask, rng, p: UnderlayParams) -> UnderlayState:
    """Redraw coordinates for masked nodes (node create / IP migration;
    reference SimpleUnderlayConfigurator::migrateNode)."""
    n = state.coords.shape[0]
    new_coords = _draw_coords(rng, n, p)
    coords = jnp.where(mask[:, None], new_coords, state.coords)
    tx_finished = jnp.where(mask, jnp.int64(0), state.tx_finished)
    if state.tcp_conn.shape[1]:
        # connections die with either endpoint (ExtTCPSocketMap): clear
        # the migrated node's own row AND every stale entry pointing at
        # the recycled slot in other nodes' caches
        stale_to = mask[jnp.clip(state.tcp_conn, 0, n - 1)] & (
            state.tcp_conn >= 0)
        state = dataclasses.replace(
            state, tcp_conn=jnp.where(mask[:, None] | stale_to, -1,
                                      state.tcp_conn))
    return dataclasses.replace(state, coords=coords,
                               tx_finished=tx_finished)


@partial(jax.jit, static_argnames=("p",))
def send_batch(state: UnderlayState, p: UnderlayParams, rng,
               src, dst, size_bytes, t_send, want, alive, kind=None):
    """Compute deliver times and drop decisions for an outbox batch.

    Args:
      src, dst: [N, M] i32 sender/receiver slots (src row i is node i).
      size_bytes: [N, M] i32 payload bytes (headers added here).
      t_send: [N, M] i64 ns logical send times.
      want: [N, M] bool — slot actually carries a message.
      alive: [N] bool.

    Returns (t_deliver [N,M] i64, ok [N,M] bool, new_state, drop_stats dict).
    Messages with ok=False are dropped (queue overrun / bit error / dest
    dead); t_deliver for self-sends is t_send (SimpleUDP.cc:322 skips the
    delay model when srcAddr == destAddr).
    """
    n, m = src.shape
    tbl = p.channel_table
    bits = (size_bytes + p.header_bytes) * 8

    tx_bw = tbl[state.channel, 0][:, None]           # [N,1] sender bandwidth
    tx_access = tbl[state.channel, 1][:, None]
    tx_ber = tbl[state.channel, 2][:, None]
    rx_bw = tbl[state.channel[dst], 0]               # [N,M] receiver side
    rx_access = tbl[state.channel[dst], 1]
    rx_ber = tbl[state.channel[dst], 2]

    self_send = src == dst
    queued = want & ~self_send

    # --- sender transmit queue (SimpleNodeEntry.cc:163-181) ---
    # Serialize this tick's messages through the sender's queue in outbox
    # order: finish_j = max(tx_finished, t_send_j) + cumsum(bw_delay).
    bw_delay_ns = jnp.where(queued, (bits.astype(F32) / tx_bw * NS), 0.0).astype(I64)
    # start of service for each message: queue may already be busy
    start0 = jnp.maximum(state.tx_finished[:, None], t_send)
    # cumulative: each message waits for all previous *sent* messages this tick
    cum = jnp.cumsum(bw_delay_ns, axis=1)
    finish = start0 + cum  # monotone approx: uses first msg's start for all
    # queue bound in bytes per the sender's own channel bandwidth
    # (SimpleNodeEntry.cc:169-180: maxQueueTime = queueBytes*8/bandwidth)
    max_queue_ns = (jnp.float32(p.send_queue_bytes * 8) / tx_bw * NS).astype(I64)
    overrun = queued & (finish - t_send > max_queue_ns)
    new_tx_finished = jnp.where(
        jnp.any(queued & ~overrun, axis=1),
        jnp.max(jnp.where(queued & ~overrun, finish, 0), axis=1),
        state.tx_finished)

    # --- propagation: coordinate distance (SimpleNodeEntry.cc:144-152) ---
    d = state.coords[:, None, :] - state.coords[dst]          # [N, M, D]
    dist = jnp.sqrt(jnp.sum(d * d, axis=-1))
    coord_delay = p.coord_delay_per_unit * dist

    rx_delay = bits.astype(F32) / rx_bw

    if p.use_coordinate_based_delay:
        total_ns = (finish - t_send) + (
            (tx_access + coord_delay + rx_delay + rx_access) * NS).astype(I64)
    else:
        total_ns = jnp.full((n, m), jnp.int64(p.constant_delay * NS))

    # --- PlanetLab delay faults (getFaultyDelay, SimpleNodeEntry.cc:
    # 197-254): errorRatio = Kumaraswamy⁻¹(hash(delay)) + shift, sign
    # from hash parity, negative ratios clamped at 0.6.  splitmix64
    # replaces the reference's SHA1-of-delay-string as the
    # deterministic delay→uniform hash (same role, integer-native).
    if p.delay_fault_type:
        a_b_shift = {"live_all": (2.03, 14.0, 0.04),
                     "live_planetlab": (1.95, 50.0, 0.105),
                     "simulation": (1.96, 23.0, 0.02)}[p.delay_fault_type]
        ka, kb, kshift = a_b_shift
        # hash the PAIR-STABLE propagation delay (coordinate distance),
        # not the full per-message delay — queue wait and serialization
        # vary per packet and would turn the stable triangle violations
        # into jitter; the ratio then distorts that propagation term
        prop_ns = (coord_delay * NS).astype(I64)
        h = prop_ns.astype(jnp.uint64)
        h = (h ^ (h >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
        h = (h ^ (h >> 27)) * jnp.uint64(0x94D049BB133111EB)
        h = h ^ (h >> 31)
        frac = (h >> 40).astype(F32) / jnp.float32(1 << 24)
        ratio = (1.0 - frac ** (1.0 / kb)) ** (1.0 / ka) + kshift
        neg = (h & 1) == 1
        ratio = jnp.where(neg, -jnp.minimum(ratio, 0.6), ratio)
        total_ns = total_ns + (ratio * prop_ns.astype(F32)).astype(I64)

    # --- SimpleTCP (tcp_kinds; SimpleTCP.cc / BaseTcpSupport):
    # direct-mapped open-connection cache — a first contact pays the
    # SYN/SYN-ACK/ACK handshake (1.5 one-way delays); a collision
    # evicts the older connection (ExtTCPSocketMap reuse semantics,
    # bounded state)
    if p.tcp_kinds and kind is not None:
        is_tcp = jnp.zeros((n, m), bool)
        for k in p.tcp_kinds:
            is_tcp = is_tcp | (kind == k)
        is_tcp = is_tcp & queued
        ct = p.tcp_connection_cache
        col_c = jnp.clip(dst % ct, 0, ct - 1)
        rows_c = jnp.broadcast_to(jnp.arange(n)[:, None], (n, m))
        open_hit = state.tcp_conn[rows_c, col_c] == dst
        handshake = is_tcp & ~open_hit
        one_way_ns = ((tx_access + coord_delay + rx_access) * NS).astype(I64)
        total_ns = total_ns + jnp.where(handshake,
                                        (one_way_ns * 3) // 2,
                                        jnp.int64(0))
        # cache write deferred until the drop decisions are known — a
        # handshake on a message lost to a partition cut / dead peer /
        # queue overrun establishes nothing

    # --- jitter: positive half-normal, sigma = jitter * delay
    # (SimpleUDP.cc:360-373 truncnormal(0, delay*jitter)) ---
    if p.jitter > 0:
        jit = jnp.abs(jax.random.normal(rng, (n, m), dtype=F32))
        total_ns = total_ns + (jit * p.jitter * total_ns.astype(F32)).astype(I64)

    # --- drops ---
    bit_err_p = 1.0 - (1.0 - tx_ber) ** bits * (1.0 - rx_ber) ** bits
    u = jax.random.uniform(jax.random.fold_in(rng, 1), (n, m), dtype=F32)
    bit_error = queued & (u < bit_err_p)
    # TCP retransmits instead of losing the segment: one RTO-scaled
    # extra delay (doubled transfer time), no drop
    if p.tcp_kinds and kind is not None:
        retrans = bit_error & is_tcp
        total_ns = total_ns + jnp.where(retrans, total_ns, jnp.int64(0))
        bit_error = bit_error & ~is_tcp
    dest_dead = want & ~alive[dst]

    # node-type partition drop (SimpleUDP.cc:349-358:
    # !areNodeTypesConnected(src, dst) → numPartitionLost)
    if p.partition_events:
        conn = connection_matrix(p, jnp.min(jnp.where(want, t_send, T_MAX)))
        part_cut = want & ~conn[state.node_type[src], state.node_type[dst]]
    else:
        part_cut = jnp.zeros_like(want)

    ok = want & ~overrun & ~bit_error & ~dest_dead & ~part_cut
    t_deliver = jnp.where(self_send, t_send, t_send + total_ns)

    if p.tcp_kinds and kind is not None:
        new_conn = state.tcp_conn.at[
            jnp.where(handshake & ok, rows_c, n), col_c].set(
            dst, mode="drop")
        state = dataclasses.replace(state, tcp_conn=new_conn)

    new_state = dataclasses.replace(state, tx_finished=new_tx_finished)
    drops = {
        "queue_lost": jnp.sum(overrun & want),
        "bit_error_lost": jnp.sum(bit_error),
        "dest_unavailable_lost": jnp.sum(dest_dead),
        "partition_lost": jnp.sum(part_cut),
    }
    return t_deliver, ok, new_state, drops
