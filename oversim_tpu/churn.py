"""Churn generators: node create/kill processes as scheduled slot events.

TPU-native equivalent of the reference's ChurnGenerator family
(src/common/{ChurnGenerator,NoChurn,LifetimeChurn,ParetoChurn,RandomChurn}):
instead of scheduling per-node create/kill self-messages through the event
kernel, every slot carries a next-create and next-kill time in an [N] i64
array and the engine flips the alive mask for the slots whose event falls
inside the tick window — churn never reshapes any array (SURVEY.md §7.2
"dynamic population": preallocated slots with alive masks, mirroring
LifetimeChurn's contextVector slot recycling, LifetimeChurn.cc:40-52).

Population conventions match the reference:
  * NoChurn (NoChurn.cc:20-52): creates one node every
    ~truncnormal(initPhaseCreationInterval, dev) until the target count,
    then signals init-finished; nodes never die.  Slots = target.
  * LifetimeChurn (LifetimeChurn.cc): 2×target context slots; during init,
    slot i (< target) is created at ~truncnormal(mean·i, dev) and killed at
    initFinished + L() where L ~ lifetime distribution; the other target
    slots go live at initFinished + L(); thereafter each kill schedules a
    re-create after a dead-time draw from the same distribution, with a
    fresh lifetime.  Distributions (LifetimeChurn.cc:distributionFunction):
    weibull (scale mean/Γ(1+1/k)), pareto_shifted, truncnormal.
  * ParetoChurn (ParetoChurn.cc:44-219): two-level process — per-slot
    individual mean life/dead times from a generalized pareto (alpha 3),
    equilibrium init-phase population (alive w.p. l/(l+d)), a stretch
    factor correcting the population-mean session to lifetimeMean, and
    residual (alpha 2) draws for the sessions in progress at init.
  * RandomChurn (RandomChurn.{h,cc}): a periodic tick every
    churnChangeInterval that probabilistically creates or removes one
    random node.
  * TraceChurn replays GlobalTraceManager traces (see trace.py).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)


def _truncnormal(rng, mean, stddev, shape=()):
    """OMNeT++ truncnormal: normal redrawn until non-negative; we fold the
    redraw into |N| which matches the half-normal-plus-shift closely enough
    for schedule jitter (exact for mean=0)."""
    x = mean + stddev * jax.random.normal(rng, shape)
    return jnp.abs(x)


@dataclasses.dataclass(frozen=True)
class ChurnParams:
    """Reference params: default.ini:498-506 + ChurnGenerator.ned."""

    model: str = "none"               # "none"|"lifetime"|"pareto"|"random"
    target_num: int = 10              # targetOverlayTerminalNum
    init_interval: float = 1.0        # initPhaseCreationInterval (s)
    init_deviation: float = 0.1
    lifetime_mean: float = 10000.0    # lifetimeMean (s)
    deadtime_mean: float | None = None  # deadtimeMean (pareto; None = life)
    lifetime_dist: str = "weibull"    # lifetimeDistName
    lifetime_par1: float = 1.0        # lifetimeDistPar1
    graceful_leave_delay: float = 15.0        # gracefulLeaveDelay, default.ini:493
    graceful_leave_probability: float = 0.5   # default.ini:494
    # per-peer rejoin context (GlobalNodeList::getContext/storeContext,
    # GlobalNodeList.h:194; BaseOverlay.cc:823-831: a node created in a
    # recycled slot reclaims the slot's previous nodeId and flags
    # instead of drawing fresh ones — LifetimeChurn context slots)
    rejoin_context: bool = False
    # RandomChurn (RandomChurn.{h,cc}): periodic probabilistic events
    churn_change_interval: float = 10.0   # churnChangeInterval
    creation_probability: float = 0.5     # creationProbability
    removal_probability: float = 0.5      # removalProbability
    # TraceChurn (TraceChurn.{h,cc} + GlobalTraceManager): precomputed
    # per-slot join/leave schedules from a trace file (trace.py parses
    # `<time> <nodeID> JOIN|LEAVE` lines into these tuples)
    trace_create: tuple = ()              # seconds, one entry per slot
    trace_kill: tuple = ()

    @property
    def num_slots(self) -> int:
        if self.model == "trace":
            return len(self.trace_create)
        if self.model == "none":
            return self.target_num
        if self.model == "pareto":
            # the reference draws nodes until `target` come up alive
            # (expected availability l/(l+d)); 3x slots bounds the draw
            return 3 * self.target_num
        return 2 * self.target_num

    @property
    def init_finished_time(self) -> float:
        """When the init phase ends and transition time starts counting."""
        if self.model == "trace":
            return 0.0
        return self.init_interval * self.target_num


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChurnState:
    t_create: jnp.ndarray  # [N] i64 — pending create events (T_INF if none)
    t_kill: jnp.ndarray    # [N] i64 — pending pre-kill (leave notification)
    t_dead: jnp.ndarray    # [N] i64 — scheduled final kill (grace window end;
                           # preKillNode schedules removal gracefulLeaveDelay
                           # later, SimpleUnderlayConfigurator.cc:375-376)
    graceful: jnp.ndarray  # [N] bool — NF_OVERLAY_NODE_GRACEFUL_LEAVE drawn
                           # (w.p. gracefulLeaveProbability, :370-373)
    l_mean: jnp.ndarray    # [N] f32 — per-slot mean lifetime (pareto)
    d_mean: jnp.ndarray    # [N] f32 — per-slot mean deadtime (pareto)
    t_tick: jnp.ndarray    # [] i64 — next periodic churn tick (random model)


def _with_grace(state_kw, n):
    state_kw.setdefault("t_dead", jnp.full((n,), T_INF, I64))
    state_kw.setdefault("graceful", jnp.zeros((n,), bool))
    return state_kw


def _draw_lifetime(rng, p: ChurnParams, shape, mean=None):
    """Session/dead-time draw (LifetimeChurn::distributionFunction).

    ``mean`` overrides ``p.lifetime_mean`` and may be a TRACED scalar —
    the campaign runner sweeps churn intensity across replicas inside
    one compiled program (oversim_tpu/campaign/).  All three
    distributions take the mean as an array-valued scale, so the same
    graph serves every replica."""
    if mean is None:
        mean = p.lifetime_mean
    if p.lifetime_dist == "weibull":
        scale = mean / math.gamma(1.0 + 1.0 / p.lifetime_par1)
        return jax.random.weibull_min(rng, scale, p.lifetime_par1, shape)
    if p.lifetime_dist == "pareto_shifted":
        k = p.lifetime_par1
        scale = mean * (k - 1.0) / k
        u = jax.random.uniform(rng, shape)
        return scale * (jnp.power(u, -1.0 / k) - 1.0)
    if p.lifetime_dist == "truncnormal":
        return _truncnormal(rng, mean, mean / 3.0, shape)
    raise ValueError(f"unknown lifetime distribution {p.lifetime_dist}")


def _shifted_pareto(rng, alpha: float, mean, shape=()):
    """ParetoChurn::shiftedPareto with betaByMean folded in
    (ParetoChurn.cc:209-219): mean*(3-1)*(u^(-1/alpha) - 1).  beta derives
    from the *schedule* alpha 3 even for the residual draw (alpha 2)."""
    u = jax.random.uniform(rng, shape, minval=1e-12, maxval=1.0)
    return mean * 2.0 * (jnp.power(u, -1.0 / alpha) - 1.0)


def init(rng: jax.Array, p: ChurnParams, life_mean=None) -> ChurnState:
    """``life_mean`` (optional, may be traced) overrides
    ``p.lifetime_mean`` for the lifetime model's session draws — the
    campaign sweep axis.  ``None`` keeps the static-param graph
    bit-identical to before."""
    n = p.num_slots
    tgt = p.target_num
    # NOTE: l_mean/d_mean must be DISTINCT arrays — a shared object
    # would alias their buffers and break run_chunk's state donation
    # (XLA rejects donating the same buffer twice)
    zeros = lambda: jnp.zeros((n,), jnp.float32)  # noqa: E731
    r1, r2, r3, r4 = jax.random.split(rng, 4)
    if p.model == "none":
        stagger = _truncnormal(r1, p.init_interval, p.init_deviation, (n,))
        t_create = jnp.cumsum(stagger)
        return ChurnState(**_with_grace(dict(
            t_create=(t_create * NS).astype(I64),
            t_kill=jnp.full((n,), T_INF, I64),
            l_mean=zeros(), d_mean=zeros(), t_tick=T_INF), n))
    if p.model == "trace":
        # TraceChurn: the schedule IS the trace (GlobalTraceManager
        # createNode/deleteNode at the traced times)
        t_create = jnp.asarray(
            [t * NS if t is not None else int(T_INF)
             for t in p.trace_create], I64)
        t_kill = jnp.asarray(
            [t * NS if t is not None else int(T_INF)
             for t in p.trace_kill], I64)
        return ChurnState(**_with_grace(dict(t_create=t_create, t_kill=t_kill,
                          l_mean=zeros(), d_mean=zeros(), t_tick=T_INF), n))
    if p.model == "lifetime":
        fin = p.init_finished_time
        i = jnp.arange(tgt)
        first_create = _truncnormal(r1, p.init_interval * i,
                                    p.init_deviation, (tgt,))
        first_kill = fin + _draw_lifetime(r2, p, (tgt,), mean=life_mean)
        second_create = fin + _draw_lifetime(r3, p, (tgt,), mean=life_mean)
        second_kill = second_create + _draw_lifetime(r4, p, (tgt,),
                                                     mean=life_mean)
        t_create = jnp.concatenate([first_create, second_create])
        t_kill = jnp.concatenate([first_kill, second_kill])
        # pre-kill (leave notification) fires gracefulLeaveDelay before
        # the session end; the node survives the grace window so total
        # session length == the drawn lifetime (LifetimeChurn.cc:112-113)
        t_kill = jnp.maximum(t_kill - p.graceful_leave_delay, t_create)
        return ChurnState(**_with_grace(dict(t_create=(t_create * NS).astype(I64),
            t_kill=(t_kill * NS).astype(I64),
            l_mean=zeros(), d_mean=zeros(), t_tick=T_INF), n))
    if p.model == "pareto":
        # ParetoChurn.cc:66-126: per-slot individual mean life/dead times,
        # equilibrium init (alive w.p. availability), stretch to hit the
        # configured global mean, residual draws for the first sessions
        fin = p.init_finished_time
        dmean = p.deadtime_mean if p.deadtime_mean is not None \
            else p.lifetime_mean
        ra, rb, rc, rd, re, rf, rg = jax.random.split(rng, 7)
        l_i = _shifted_pareto(ra, 3.0, p.lifetime_mean, (n,))
        d_i = _shifted_pareto(rb, 3.0, dmean, (n,))
        avail = l_i / (l_i + d_i)
        alive0 = jax.random.uniform(rc, (n,)) < avail
        # the reference draws slots until `tgt` come up alive
        # (ParetoChurn.cc:71): only slots up to (and including) the
        # tgt-th alive draw participate; later slots never exist — this
        # keeps the long-run population at target (each participating
        # slot contributes availability a_i, sum ≈ tgt)
        alive_rank = jnp.cumsum(alive0.astype(jnp.int32))
        is_init_alive = alive0 & (alive_rank <= tgt)
        participating = alive_rank <= tgt
        # (if fewer than tgt come up alive — vanishingly unlikely with 3x
        # slots — the surplus dead slots simply all participate)
        # stretch normalization over exactly the participating population
        # (ParetoChurn.cc normalizes over the drawn slots, not the 3x pool)
        sum_li = jnp.sum(jnp.where(participating, 1.0 / (l_i + d_i), 0.0))
        mean_life = jnp.sum(
            jnp.where(participating, l_i / ((l_i + d_i) * sum_li), 0.0))
        stretch = p.lifetime_mean / mean_life
        l_i = l_i * stretch
        d_i = d_i * stretch
        live_idx = jnp.where(is_init_alive, alive_rank - 1, 0)
        stagger = _truncnormal(rd, p.init_interval * live_idx,
                               p.init_deviation, (n,))
        res_l = _shifted_pareto(re, 2.0, l_i, (n,))
        res_d = _shifted_pareto(rf, 2.0, d_i, (n,))
        t_create = jnp.where(is_init_alive, stagger, fin + res_d)
        first_life = jnp.where(is_init_alive, fin - stagger + res_l,
                               _shifted_pareto(rg, 3.0, l_i, (n,)))
        t_kill = jnp.maximum(t_create + first_life - p.graceful_leave_delay,
                             t_create)
        t_create = jnp.where(participating, t_create, T_INF / NS)
        t_kill = jnp.where(participating, t_kill, T_INF / NS)
        return ChurnState(**_with_grace(dict(t_create=(t_create * NS).astype(I64),
            t_kill=(t_kill * NS).astype(I64),
            l_mean=l_i.astype(jnp.float32), d_mean=d_i.astype(jnp.float32),
            t_tick=T_INF), n))
    if p.model == "random":
        # RandomChurn: start tgt nodes, then probabilistic create/remove
        # ticks every churnChangeInterval (step() drives the process)
        stagger = _truncnormal(r1, p.init_interval, p.init_deviation, (n,))
        t_create = jnp.cumsum(stagger)
        t_create = jnp.where(jnp.arange(n) < tgt, t_create, T_INF / NS)
        return ChurnState(**_with_grace(dict(
            t_create=(t_create * NS).astype(I64),
            t_kill=jnp.full((n,), T_INF, I64),
            l_mean=zeros(), d_mean=zeros(),
            t_tick=jnp.int64(int((p.init_finished_time
                                  + p.churn_change_interval) * NS))), n))
    raise ValueError(f"unknown churn model {p.model}")


def next_event(state: ChurnState):
    # t_kill holds the already-fired pre-kill time during a grace window
    # (rebirth anchor) — mask it so the engine doesn't spin on it
    kill_eff = jnp.where(state.t_dead < T_INF, T_INF, state.t_kill)
    t = jnp.minimum(state.t_tick,
                    jnp.minimum(jnp.min(state.t_create),
                                jnp.min(kill_eff)))
    return jnp.minimum(t, jnp.min(state.t_dead))


def step(state: ChurnState, p: ChurnParams, alive, t_start, t_end, rng,
         life_mean=None):
    """Fire create/pre-kill/kill events inside [t_start, t_end).

    Returns (state', created, killed, leaving — all [N] bool).  A pre-kill
    (t_kill) starts the grace window: the node keeps running for
    gracefulLeaveDelay, is removed from the bootstrap oracle, and — w.p.
    gracefulLeaveProbability — receives the graceful-leave notification
    (``state.graceful``) so overlay/apps can hand data over
    (SimpleUnderlayConfigurator::preKillNode, :312-377).  The final kill
    (t_dead) frees the slot and schedules its next incarnation
    (LifetimeChurn::deleteNode re-creates after a dead-time draw).
    ``leaving`` marks the pre-kills fired THIS window.
    """
    created = (state.t_create < t_end) & ~alive
    leaving = (state.t_kill < t_end) & alive & ~created & (
        state.t_dead >= T_INF)
    killed = (state.t_dead < t_end) & alive & ~created

    r_grace, rng = jax.random.split(rng)
    grace_ns = jnp.int64(int(p.graceful_leave_delay * NS))
    coin = jax.random.uniform(r_grace, (p.num_slots,)) \
        < p.graceful_leave_probability
    t_dead = jnp.where(leaving, state.t_kill + grace_ns, state.t_dead)
    graceful = jnp.where(leaving, coin, state.graceful)
    t_dead = jnp.where(killed, T_INF, t_dead)
    graceful = jnp.where(killed, False, graceful)
    # t_kill keeps the pre-kill time through the grace window: the rebirth
    # dead-time below starts at deleteNode (= the pre-kill), matching
    # LifetimeChurn::deleteNode; next_event() masks it while t_dead runs

    t_create = jnp.where(created, T_INF, state.t_create)
    t_kill = state.t_kill
    t_tick = state.t_tick
    n = p.num_slots

    if p.model == "lifetime":
        r1, r2 = jax.random.split(rng)
        dead_time = (_draw_lifetime(r1, p, (n,), mean=life_mean)
                     * NS).astype(I64)
        lifetime = (_draw_lifetime(r2, p, (n,), mean=life_mean)
                    * NS).astype(I64)
        next_create = state.t_kill + dead_time
        next_kill = jnp.maximum(next_create + lifetime - grace_ns,
                                next_create)
        t_create = jnp.where(killed, next_create, t_create)
        t_kill = jnp.where(killed, next_kill, t_kill)
    elif p.model == "pareto":
        # ParetoChurn::deleteNode (ParetoChurn.cc:182-196): rebirth after
        # individualLifetime(d_i), next session individualLifetime(l_i)
        r1, r2 = jax.random.split(rng)
        dead_time = (_shifted_pareto(r1, 3.0, state.d_mean, (n,))
                     * NS).astype(I64)
        lifetime = (_shifted_pareto(r2, 3.0, state.l_mean, (n,))
                    * NS).astype(I64)
        next_create = state.t_kill + dead_time
        next_kill = jnp.maximum(next_create + lifetime - grace_ns,
                                next_create)
        t_create = jnp.where(killed, next_create, t_create)
        t_kill = jnp.where(killed, next_kill, t_kill)
    elif p.model == "random":
        # RandomChurn::handleMessage: every churnChangeInterval flip a coin
        # for one create and one removal (probabilistic population drift)
        t_kill = jnp.where(killed, T_INF, t_kill)
        del n  # slots indexed directly below
        tick = t_tick < t_end
        r1, r2, r3, r4 = jax.random.split(rng, 4)
        do_create = tick & (jax.random.uniform(r1) < p.creation_probability)
        do_remove = tick & (jax.random.uniform(r2) < p.removal_probability)
        cur_alive = (alive | created) & ~killed
        # random dead slot → create now; random alive slot → kill now
        dead_w = jnp.where(~cur_alive & (t_create >= T_INF), 1.0, 0.0)
        alive_w = jnp.where(cur_alive, 1.0, 0.0)
        has_dead = jnp.sum(dead_w) > 0
        has_alive = jnp.sum(alive_w) > 0
        di = jax.random.categorical(r3, jnp.log(jnp.maximum(dead_w, 1e-30)))
        ai = jax.random.categorical(r4, jnp.log(jnp.maximum(alive_w, 1e-30)))
        t_create = t_create.at[di].set(
            jnp.where(do_create & has_dead, t_end, t_create[di]))
        t_kill = t_kill.at[ai].set(
            jnp.where(do_remove & has_alive, t_end, t_kill[ai]))
        t_tick = jnp.where(
            tick, t_tick + jnp.int64(int(p.churn_change_interval * NS)),
            t_tick)
    else:
        t_kill = jnp.where(killed, T_INF, t_kill)
    # a next-incarnation pre-kill drawn inside the current window must be
    # DEFERRED past it (cancelling would make the slot immortal; leaving
    # it stale would pin the event horizon)
    t_kill = jnp.where(killed & (t_kill <= t_end), t_end + 1, t_kill)

    return ChurnState(
        t_create=t_create, t_kill=t_kill, t_dead=t_dead, graceful=graceful,
        l_mean=state.l_mean, d_mean=state.d_mean,
        t_tick=t_tick), created, killed, leaving
