"""Churn generators: node create/kill processes as scheduled slot events.

TPU-native equivalent of the reference's ChurnGenerator family
(src/common/{ChurnGenerator,NoChurn,LifetimeChurn,ParetoChurn,RandomChurn}):
instead of scheduling per-node create/kill self-messages through the event
kernel, every slot carries a next-create and next-kill time in an [N] i64
array and the engine flips the alive mask for the slots whose event falls
inside the tick window — churn never reshapes any array (SURVEY.md §7.2
"dynamic population": preallocated slots with alive masks, mirroring
LifetimeChurn's contextVector slot recycling, LifetimeChurn.cc:40-52).

Population conventions match the reference:
  * NoChurn (NoChurn.cc:20-52): creates one node every
    ~truncnormal(initPhaseCreationInterval, dev) until the target count,
    then signals init-finished; nodes never die.  Slots = target.
  * LifetimeChurn (LifetimeChurn.cc): 2×target context slots; during init,
    slot i (< target) is created at ~truncnormal(mean·i, dev) and killed at
    initFinished + L() where L ~ lifetime distribution; the other target
    slots go live at initFinished + L(); thereafter each kill schedules a
    re-create after a dead-time draw from the same distribution, with a
    fresh lifetime.  Distributions (LifetimeChurn.cc:distributionFunction):
    weibull (scale mean/Γ(1+1/k)), pareto_shifted, truncnormal.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)


def _truncnormal(rng, mean, stddev, shape=()):
    """OMNeT++ truncnormal: normal redrawn until non-negative; we fold the
    redraw into |N| which matches the half-normal-plus-shift closely enough
    for schedule jitter (exact for mean=0)."""
    x = mean + stddev * jax.random.normal(rng, shape)
    return jnp.abs(x)


@dataclasses.dataclass(frozen=True)
class ChurnParams:
    """Reference params: default.ini:498-506 + ChurnGenerator.ned."""

    model: str = "none"               # "none" | "lifetime"
    target_num: int = 10              # targetOverlayTerminalNum
    init_interval: float = 1.0        # initPhaseCreationInterval (s)
    init_deviation: float = 0.1
    lifetime_mean: float = 10000.0    # lifetimeMean (s)
    lifetime_dist: str = "weibull"    # lifetimeDistName
    lifetime_par1: float = 1.0        # lifetimeDistPar1
    graceful_leave_delay: float = 15.0

    @property
    def num_slots(self) -> int:
        return self.target_num if self.model == "none" else 2 * self.target_num

    @property
    def init_finished_time(self) -> float:
        """When the init phase ends and transition time starts counting."""
        return self.init_interval * self.target_num


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChurnState:
    t_create: jnp.ndarray  # [N] i64 — pending create events (T_INF if none)
    t_kill: jnp.ndarray    # [N] i64 — pending kill events


def _draw_lifetime(rng, p: ChurnParams, shape):
    """Session/dead-time draw (LifetimeChurn::distributionFunction)."""
    if p.lifetime_dist == "weibull":
        scale = p.lifetime_mean / math.gamma(1.0 + 1.0 / p.lifetime_par1)
        return jax.random.weibull_min(rng, scale, p.lifetime_par1, shape)
    if p.lifetime_dist == "pareto_shifted":
        k = p.lifetime_par1
        scale = p.lifetime_mean * (k - 1.0) / k
        u = jax.random.uniform(rng, shape)
        return scale * (jnp.power(u, -1.0 / k) - 1.0)
    if p.lifetime_dist == "truncnormal":
        return _truncnormal(rng, p.lifetime_mean, p.lifetime_mean / 3.0, shape)
    raise ValueError(f"unknown lifetime distribution {p.lifetime_dist}")


def init(rng: jax.Array, p: ChurnParams) -> ChurnState:
    n = p.num_slots
    tgt = p.target_num
    r1, r2, r3, r4 = jax.random.split(rng, 4)
    if p.model == "none":
        stagger = _truncnormal(r1, p.init_interval, p.init_deviation, (n,))
        t_create = jnp.cumsum(stagger)
        return ChurnState(
            t_create=(t_create * NS).astype(I64),
            t_kill=jnp.full((n,), T_INF, I64))
    if p.model == "lifetime":
        fin = p.init_finished_time
        i = jnp.arange(tgt)
        first_create = _truncnormal(r1, p.init_interval * i,
                                    p.init_deviation, (tgt,))
        first_kill = fin + _draw_lifetime(r2, p, (tgt,))
        second_create = fin + _draw_lifetime(r3, p, (tgt,))
        second_kill = second_create + _draw_lifetime(r4, p, (tgt,))
        t_create = jnp.concatenate([first_create, second_create])
        t_kill = jnp.concatenate([first_kill, second_kill])
        # kill fires gracefulLeaveDelay before the end of the session
        t_kill = jnp.maximum(t_kill - p.graceful_leave_delay, t_create)
        return ChurnState(
            t_create=(t_create * NS).astype(I64),
            t_kill=(t_kill * NS).astype(I64))
    raise ValueError(f"unknown churn model {p.model}")


def next_event(state: ChurnState):
    return jnp.minimum(jnp.min(state.t_create), jnp.min(state.t_kill))


def step(state: ChurnState, p: ChurnParams, alive, t_start, t_end, rng):
    """Fire create/kill events inside [t_start, t_end).

    Returns (state', created [N] bool, killed [N] bool).  A kill immediately
    schedules the slot's next incarnation (LifetimeChurn::deleteNode
    re-creates after a dead-time draw with a fresh lifetime draw).
    """
    created = (state.t_create < t_end) & ~alive
    killed = (state.t_kill < t_end) & alive & ~created

    t_create = jnp.where(created, T_INF, state.t_create)
    t_kill = state.t_kill

    if p.model == "lifetime":
        n = p.num_slots
        r1, r2 = jax.random.split(rng)
        dead_time = (_draw_lifetime(r1, p, (n,)) * NS).astype(I64)
        lifetime = (_draw_lifetime(r2, p, (n,)) * NS).astype(I64)
        graceful = jnp.int64(p.graceful_leave_delay * NS)
        next_create = state.t_kill + dead_time
        next_kill = jnp.maximum(next_create + lifetime - graceful, next_create)
        t_create = jnp.where(killed, next_create, t_create)
        t_kill = jnp.where(killed, next_kill, t_kill)
    else:
        t_kill = jnp.where(killed, T_INF, t_kill)

    return ChurnState(t_create=t_create, t_kill=t_kill), created, killed
