"""Device-resident telemetry plane: in-graph KPI time series + exporters.

The reference streams every statistic through the GlobalStatistics
singleton as it happens — cOutVector rows into ``results/*.vec`` plus
finish()-time scalars (GlobalStatistics.cc recordScalar/addStdDev) — so
a run is observable while it runs.  The TPU build's device-resident run
loops (``run_chunk`` / ``run_until_device``, one dispatch per bench
window) made a million-tick window a black box between dispatch and
fetch: only the END-of-window accumulator values came back.

This module restores the time axis WITHOUT giving up the one-dispatch /
one-``device_get`` contract: preallocated ``[W, ...]`` ring buffers ride
as one extra ``SimState`` leaf (``SimState.telemetry``) and a sample is
folded in every ``TelemetryParams.sample_ticks`` ticks INSIDE the jitted
tick (engine/sim.py ``_phase_alloc_stats``).  Each sample snapshots

  * the cumulative stats accumulators of the tapped keys ("s:" [5]
    accumulators, "h:" histograms, "c:" counters — the app's
    ``kpi_spec()`` registry picks the taps, see apps/base.py),
  * every engine drop/overflow counter (sim.ENGINE_COUNTERS),
  * the alive population, sim time and tick number.

The write is a gated scatter (``buf.at[idx].set(v, mode="drop")`` with
``idx == W`` on non-sample ticks — out of bounds drops to a no-op), so
telemetry adds a bounded number of scatters and ZERO sorts/collectives
to the tick (pinned by scripts/hlo_breakdown.py --telemetry), consumes
no rng, and leaves every non-telemetry leaf bit-identical to a
telemetry-off run (tests/test_zz_telemetry_identity.py).  Under the
campaign vmap the buffers stack to ``[S, W, ...]`` and shard over the
replica axis like any other leaf — per-replica KPI series with
cross-replica CI bands via ``stats.series_summary``.

Host-side exporters (all dependency-free):

  * ``kpi_series`` — ring unwrap into named, time-ordered series
    (``name.mean`` / ``name.count`` for scalar accumulators, raw counts
    for counters, ``engine.*`` for the drop counters, ``aliveNodes``,
    derived ``kbr_delivery_ratio``) + raw histogram snapshots;
  * ``write_vec`` — the series as OMNeT++ .vec rows through
    recorder.py's writer (native vecwriter.c or the byte-identical
    Python fallback);
  * ``PerfettoTrace`` — Chrome-trace/Perfetto JSON (``traceEvents``)
    for bench window dispatch/fetch spans, profiling.py per-tick phase
    breakdowns (``add_profile``) and KPI counter tracks
    (``add_series``); load in ui.perfetto.dev or chrome://tracing;
  * ``run_manifest`` — the unified RunManifest (config hash, mesh/
    sharding layout, HLO op-budget results, git rev, artifact paths)
    attached to every bench/campaign/scale_smoke artifact
    (bench.ArtifactWriter.set_manifest).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess

import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32
I64 = jnp.int64
F64 = jnp.float64
NS = 1_000_000_000


@dataclasses.dataclass(frozen=True)
class TelemetryParams:
    """Static telemetry shape (``**.telemetry.*`` ini keys).

    ``sample_ticks``  — snapshot period in ticks; 0 (default) disables
                        telemetry entirely (SimState.telemetry = None,
                        zero graph cost, bit-identical state layout).
    ``window``        — W, the ring capacity: the LAST ``window``
                        samples survive (older ones are overwritten
                        in ring order).
    ``include``       — stat-key substring filters; empty = the app's
                        ``kpi_spec()`` registry (apps/base.py), or every
                        stats key when the app declares none.
    """

    sample_ticks: int = 0
    window: int = 256
    include: tuple = ()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TelemetryState:
    """Ring buffers carried as a SimState leaf.  ``n`` counts samples
    taken so far; sample ``j`` (0-based) lives at row ``j % W`` — the
    ring holds the last ``min(n, W)`` samples."""

    n: jnp.ndarray            # i64 scalar — total samples taken
    t_ns: jnp.ndarray         # [W] i64 — sim time of each sample
    tick: jnp.ndarray         # [W] i64 — tick number of each sample
    alive: jnp.ndarray        # [W] i64 — alive population
    series: dict              # stats key -> [W, *leaf.shape] snapshots
    counters: dict            # engine counter name -> [W] i64


def resolve_taps(stats: dict, tp: TelemetryParams, app=None) -> tuple:
    """Pick which stats keys the ring snapshots.

    Priority: explicit ``include`` substring filters > the app's
    ``kpi_spec()`` registry (names without the "s:"/"h:"/"c:" class
    prefix) > every key.  An app registry that matches nothing falls
    back to every key rather than recording an empty plane."""
    keys = tuple(stats)
    if tp.include:
        sel = tuple(k for k in keys if any(p in k for p in tp.include))
        return sel or keys
    if app is not None and hasattr(app, "kpi_spec"):
        want = set(app.kpi_spec())
        sel = tuple(k for k in keys if k[2:] in want)
        return sel or keys
    return keys


def init(stats: dict, counter_names, tp: TelemetryParams,
         app=None) -> TelemetryState | None:
    """Zeroed ring buffers for the resolved taps; None when disabled."""
    if tp is None or tp.sample_ticks <= 0:
        return None
    w = int(tp.window)
    if w < 1:
        raise ValueError(f"telemetry.window must be >= 1, got {w}")
    taps = resolve_taps(stats, tp, app=app)
    return TelemetryState(
        n=jnp.zeros((), I64),
        t_ns=jnp.zeros((w,), I64),
        tick=jnp.zeros((w,), I64),
        alive=jnp.zeros((w,), I64),
        series={k: jnp.zeros((w,) + stats[k].shape, stats[k].dtype)
                for k in taps},
        counters={name: jnp.zeros((w,), I64) for name in counter_names},
    )


def fold(tel: TelemetryState | None, tp: TelemetryParams, *, t_end, tick,
         alive, stats: dict, counters: dict):
    """In-graph sample point (called from ``_phase_alloc_stats`` with
    the END-of-tick values).  On non-sample ticks the write index is W —
    ``mode="drop"`` turns every scatter into a no-op — so the only
    divergent state is ``n``.  No rng, no sorts, no collectives."""
    if tel is None or tp is None or tp.sample_ticks <= 0:
        return tel
    w = tel.t_ns.shape[-1]
    do = (tick % jnp.int64(tp.sample_ticks)) == 0
    idx = jnp.where(do, (tel.n % w).astype(I32), jnp.int32(w))
    put = lambda buf, v: buf.at[idx].set(  # noqa: E731
        jnp.asarray(v).astype(buf.dtype), mode="drop")
    return TelemetryState(
        n=tel.n + do.astype(I64),
        t_ns=put(tel.t_ns, t_end),
        tick=put(tel.tick, tick),
        alive=put(tel.alive, jnp.sum(alive)),
        series={k: put(buf, stats[k]) for k, buf in tel.series.items()},
        counters={k: put(buf, counters[k])
                  for k, buf in tel.counters.items()},
    )


# ---------------------------------------------------------------------------
# host-side: ring unwrap + KPI series
# ---------------------------------------------------------------------------

def _ring_order(n: int, w: int) -> np.ndarray:
    """Row indices oldest-first for a ring that has taken n samples."""
    if n <= w:
        return np.arange(n)
    return (n + np.arange(w)) % w


def unwrap(tel) -> dict:
    """Time-order a (device_get of a) TelemetryState's rings.

    Returns {"k": samples kept, "n": samples taken, "t_ns"/"tick"/
    "alive": [K] arrays, "series": {key: [K, ...]}, "counters":
    {name: [K]}} — oldest sample first."""
    n = int(np.asarray(tel.n))
    w = int(np.asarray(tel.t_ns).shape[-1])
    order = _ring_order(n, w)
    take = lambda buf: np.asarray(buf)[order]  # noqa: E731
    return {
        "k": len(order), "n": n,
        "t_ns": take(tel.t_ns), "tick": take(tel.tick),
        "alive": take(tel.alive),
        "series": {k: take(v) for k, v in tel.series.items()},
        "counters": {k: take(v) for k, v in tel.counters.items()},
    }


def kpi_series(tel) -> dict:
    """Flat, named KPI time series off a fetched TelemetryState.

    Output: {"k", "n", "t_s": [K], "tick": [K], "series":
    {flat_name: float [K]}, "hists": {name: int [K, B]}}.  Scalar
    accumulators ("s:name", cumulative (n, sum, sumsq, min, max))
    become ``name.mean`` (NaN until the first event) and ``name.count``;
    counters keep their name; engine counters get an ``engine.`` prefix;
    the alive population is ``aliveNodes``; ``kbr_delivery_ratio`` is
    derived when the KBRTest counters are tapped.  Histogram snapshots
    stay 2-D in ``hists`` (per-sample bin counts)."""
    u = unwrap(tel)
    series = {"aliveNodes": u["alive"].astype(float)}
    hists = {}
    for key, v in u["series"].items():
        name = key[2:]
        v = np.asarray(v)
        if key.startswith("s:"):
            cnt = v[:, 0]
            with np.errstate(invalid="ignore", divide="ignore"):
                series[name + ".mean"] = np.where(
                    cnt > 0, v[:, 1] / np.maximum(cnt, 1.0), np.nan)
            series[name + ".count"] = cnt
        elif key.startswith("h:"):
            hists[name] = v
        else:
            series[name] = v.astype(float)
    for name, v in u["counters"].items():
        series["engine." + name] = np.asarray(v, float)
    if "kbr_sent" in series and "kbr_delivered" in series:
        sent = series["kbr_sent"]
        with np.errstate(invalid="ignore", divide="ignore"):
            series["kbr_delivery_ratio"] = np.where(
                sent > 0, series["kbr_delivered"] / np.maximum(sent, 1.0),
                np.nan)
    return {"k": u["k"], "n": u["n"],
            "t_s": u["t_ns"].astype(float) / NS,
            "tick": u["tick"], "series": series, "hists": hists}


def series_report(tel) -> dict:
    """JSON-safe form of ``kpi_series`` (lists, NaN -> None) — the
    per-window/artifact record shape."""
    ks = kpi_series(tel)
    clean = lambda a: [None if (isinstance(x, float) and x != x)  # noqa: E731
                       else float(x) for x in np.asarray(a, float)]
    return {
        "metric": "telemetry_series", "samples": ks["k"],
        "samples_taken": ks["n"],
        "t_s": clean(ks["t_s"]),
        "tick": np.asarray(ks["tick"]).astype(int).tolist(),
        "series": {k: clean(v) for k, v in ks["series"].items()},
        "hists": {k: np.asarray(v).astype(int).tolist()
                  for k, v in ks["hists"].items()},
    }


def write_vec(tel_or_series, path, run_id: str = "telemetry-0",
              module: str = "OverSimTpu.telemetry") -> int:
    """Flush KPI series as OMNeT++ .vec rows through recorder.py's
    writer (native vecwriter.c when it builds, byte-identical Python
    fallback otherwise).  Accepts a TelemetryState or a ``kpi_series``
    dict; returns the number of vectors written.  Histogram snapshots
    are .vec-inexpressible (2-D) and are left to the JSON exporters."""
    from oversim_tpu import recorder
    ks = (tel_or_series if isinstance(tel_or_series, dict)
          else kpi_series(tel_or_series))
    w = recorder._writer(path, run_id)
    try:
        t = np.asarray(ks["t_s"], float)
        for name in sorted(ks["series"]):
            vid = w.declare(module, name)
            w.rows(vid, t, np.nan_to_num(
                np.asarray(ks["series"][name], float)))
    finally:
        w.close()
    return len(ks["series"])


# ---------------------------------------------------------------------------
# cross-replica ensemble series (campaign tier)
# ---------------------------------------------------------------------------

def ensemble_series(tel_stacked, confidence: float = 0.95) -> dict:
    """Per-replica KPI series + cross-replica CI bands off a fetched
    ``[S, W, ...]``-stacked TelemetryState (campaign runner).

    Replicas tick on independent event horizons but share the sampling
    cadence (every ``sample_ticks`` ticks), so sample index j is
    comparable across replicas; series are truncated to the shortest
    replica before banding.  Returns {"enabled", "samples", "replicas",
    "tick": [K], "t_s": per-replica [S][K], "per_replica":
    {name: [S][K]}, "bands": {name: stats.series_summary schema}}."""
    from oversim_tpu import stats as stats_mod
    s_count = int(np.asarray(tel_stacked.n).shape[0])
    per = [kpi_series(jax.tree.map(lambda x: np.asarray(x)[r], tel_stacked))
           for r in range(s_count)]
    k = min(p["k"] for p in per)
    names = sorted(per[0]["series"])
    clean = lambda a: [None if (isinstance(x, float) and x != x)  # noqa: E731
                       else float(x) for x in np.asarray(a, float)]
    stacked = {name: np.stack([p["series"][name][:k] for p in per])
               for name in names}
    return {
        "enabled": True, "samples": k, "replicas": s_count,
        "confidence": confidence,
        "tick": (np.asarray(per[0]["tick"][:k]).astype(int).tolist()
                 if k else []),
        "t_s": [clean(p["t_s"][:k]) for p in per],
        "per_replica": {name: [clean(row) for row in stacked[name]]
                        for name in names},
        "bands": {name: stats_mod.series_summary(stacked[name], confidence)
                  for name in names},
    }


# ---------------------------------------------------------------------------
# Perfetto / Chrome-trace exporter
# ---------------------------------------------------------------------------

class PerfettoTrace:
    """Chrome-trace-JSON builder (the format ui.perfetto.dev and
    chrome://tracing both load).  Timestamps are absolute seconds
    (``time.perf_counter`` readings); the writer rebases to the first
    event so traces start at 0."""

    def __init__(self, process_name: str = "oversim-tpu"):
        self.events = []
        self.process_name = process_name

    def span(self, name, t0_s, dur_s, *, tid=0, pid=0, args=None):
        """Complete event ("ph": "X"): a [t0, t0+dur) slice."""
        ev = {"name": name, "ph": "X", "ts": float(t0_s) * 1e6,
              "dur": max(float(dur_s), 0.0) * 1e6, "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def instant(self, name, t_s, *, tid=0, pid=0, args=None):
        ev = {"name": name, "ph": "i", "ts": float(t_s) * 1e6,
              "pid": pid, "tid": tid, "s": "t"}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def counter(self, name, t_s, value, *, pid=0):
        self.events.append({"name": name, "ph": "C",
                            "ts": float(t_s) * 1e6, "pid": pid,
                            "args": {name: float(value)}})

    def add_profile(self, report: dict, *, t0_s: float = 0.0, tid=1):
        """Lay a profiling.py report's per-tick phase durations out as
        back-to-back spans (one track per call).  Uses the per-tick
        ``phase_ticks_ms`` list when present, else one averaged tick
        from ``phase_ms_per_tick``."""
        ticks = report.get("phase_ticks_ms")
        if not ticks:
            avg = report.get("phase_ms_per_tick")
            ticks = [avg] if avg else []
        t = t0_s
        for i, phases in enumerate(ticks):
            for phase, ms in phases.items():
                self.span(f"tick.{phase}", t, ms / 1e3, tid=tid,
                          args={"tick_index": i})
                t += ms / 1e3
        return t

    def add_series(self, ks: dict, *, pid=2,
                   names: tuple | None = None):
        """KPI counter tracks from a ``kpi_series`` dict — the time axis
        is SIMULATED seconds (its own pid so sim-time tracks don't
        interleave with wall-clock spans).  An ``ensemble_series``
        record (``bands``) emits ``name.mean`` plus ``name.ci_lo`` /
        ``name.ci_hi`` band-edge tracks instead of raw values."""
        if "bands" in ks:
            t = np.asarray(ks["t_s"][0] if ks.get("t_s") else [], float)
            for name in (names or sorted(ks["bands"])):
                b = ks["bands"][name]
                mean = np.asarray(b["mean"], float)
                ci = b.get("ci")
                ci = np.asarray(ci if ci is not None
                                else [np.nan] * len(mean), float)
                for ti, m, c in zip(t, mean, ci):
                    if m != m:                     # skip NaN gaps
                        continue
                    self.counter(f"{name}.mean", ti, m, pid=pid)
                    if c == c:
                        self.counter(f"{name}.ci_lo", ti, m - c, pid=pid)
                        self.counter(f"{name}.ci_hi", ti, m + c, pid=pid)
            return
        t = np.asarray(ks["t_s"], float)
        for name in (names or sorted(ks["series"])):
            vals = np.asarray(ks["series"][name], float)
            for ti, vi in zip(t, vals):
                if vi == vi:                       # skip NaN gaps
                    self.counter(name, ti, vi, pid=pid)

    def to_dict(self) -> dict:
        base = min((e["ts"] for e in self.events), default=0.0)
        events = []
        for e in self.events:
            e = dict(e)
            e["ts"] = round(e["ts"] - base, 3)
            events.append(e)
        meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": name}}
                for pid, name in ((0, self.process_name),
                                  (2, "sim-time KPIs"))
                if any(e.get("pid") == pid for e in events)]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def write(self, path) -> None:
        """Atomic write (tmp + replace) so a kill mid-run leaves the
        previous complete trace."""
        tmp = str(path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, str(path))


# ---------------------------------------------------------------------------
# RunManifest
# ---------------------------------------------------------------------------

def config_hash(config) -> str:
    """Stable sha256 over a JSON-serializable config mapping (sorted
    keys, default=str for dataclasses/paths)."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def git_rev(root=None) -> str | None:
    try:
        r = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=10,
            cwd=root or os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        return r.stdout.strip() or None if r.returncode == 0 else None
    except (OSError, subprocess.TimeoutExpired):
        return None


def mesh_layout(mesh=None) -> dict:
    """Mesh/sharding description for the manifest; with no mesh, the
    visible-device inventory."""
    out = {}
    try:
        devs = jax.devices()
        out["devices"] = len(devs)
        out["platform"] = devs[0].platform if devs else None
    except Exception:  # noqa: BLE001 — manifests must never kill a run
        pass
    if mesh is not None:
        out["mesh_axes"] = {str(k): int(v)
                            for k, v in mesh.shape.items()}
    return out


def analysis_verdict(path=None):
    """Compact graph-contract verdict for the manifest's ``hlo_budget``
    field, read from the analyzer's JSON document (``scripts/analyze.py
    --json``).  ``path`` defaults to $OVERSIM_ANALYSIS_VERDICT — which
    scripts/run_suite.sh exports after its analyze gate — so every
    bench/campaign/service artifact records which contract revision its
    graphs passed.  None when no verdict document is available."""
    import json
    import os
    path = path or os.environ.get("OVERSIM_ANALYSIS_VERDICT")
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    from oversim_tpu.analysis.findings import verdict_summary
    return verdict_summary(doc)


def env_knobs(environ=None) -> dict:
    """Every effective ``OVERSIM_*`` environment knob, sorted — the
    run-shaping side channel (OVERSIM_AOT, OVERSIM_BENCH_*,
    OVERSIM_XPROF, OVERSIM_METRICS_PORT, ...) that the flags/ini config
    does NOT capture, so a manifest alone reproduces the run."""
    env = os.environ if environ is None else environ
    return {k: env[k] for k in sorted(env) if k.startswith("OVERSIM")}


def run_manifest(*, config=None, mesh=None, hlo_budget=None,
                 artifacts=None, extra=None) -> dict:
    """The unified RunManifest attached to every bench/campaign/
    scale_smoke artifact: enough provenance to re-run or audit the
    measurement — config hash (and the config itself), mesh/sharding
    layout, HLO op-budget results, git rev, artifact paths, effective
    OVERSIM_* env knobs, runtime versions.  ``hlo_budget`` defaults to
    :func:`analysis_verdict` (the graph-contract analyzer's verdict
    document, when one is present)."""
    import platform as _platform
    if hlo_budget is None:
        hlo_budget = analysis_verdict()
    man = {
        "metric": "run_manifest",
        "kind": "run_manifest",
        "git_rev": git_rev(),
        "config": config,
        "config_hash": config_hash(config) if config is not None else None,
        "mesh": mesh_layout(mesh),
        "hlo_budget": hlo_budget,
        "artifacts": artifacts or {},
        "env": env_knobs(),
        "versions": {"python": _platform.python_version(),
                     "jax": getattr(jax, "__version__", None)},
    }
    if extra:
        man.update(extra)
    return man
