"""OMNeT++-style .ini configuration parser.

Host-side front-end reimplementing the configuration surface the reference
relies on (SURVEY.md §2.6 "Config/CLI"; reference behavior defined by the
OMNeT++ ini format as used in simulations/default.ini + omnetpp.ini):

  * ``[General]`` and ``[Config Name]`` sections; ``extends = Other`` and
    the implicit fallback of every config to General;
  * ``include ./default.ini`` directives (verify.ini:55);
  * hierarchical wildcard parameter keys
    (``**.overlay*.chord.stabilizeDelay = 20s``): ``*`` matches within one
    dot-separated path segment, ``**`` matches across segments;
    first matching assignment wins, searched config-section-first then
    through the extends chain to General (OMNeT++ precedence);
  * value literals: quantities with units (``60s``, ``100B``, ``10Mbps``),
    booleans, ints, floats, quoted strings;
  * ``${a,b,c}`` / ``${x=1..5 step 2}`` parameter-study iteration values
    (thesis.ini:16) — exposed as `Study` objects so a driver can expand
    the cartesian product of run variants.

This module is pure Python (no jax): it runs once at simulation-build
time; the resolved values feed the static dataclass params of the engine.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

_UNIT_SCALE = {
    "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9, "ps": 1e-12,
    "m": 60.0, "h": 3600.0, "d": 86400.0,
    "B": 1.0, "KiB": 1024.0, "MiB": 1024.0 ** 2, "GiB": 1024.0 ** 3,
    "KB": 1e3, "MB": 1e6, "GB": 1e9,
    "bps": 1.0, "Kbps": 1e3, "Mbps": 1e6, "Gbps": 1e9,
}

_QUANTITY_RE = re.compile(
    r"^([+-]?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)\s*([a-zA-Z]+)$")
_STUDY_RE = re.compile(r"^\$\{(.*)\}$")


@dataclasses.dataclass(frozen=True)
class Study:
    """A ``${...}`` parameter-study placeholder: iterate ``values``."""

    name: str | None
    values: tuple

    def default(self):
        return self.values[0]


def parse_value(raw: str):
    """Parse one ini value literal into a python object."""
    raw = raw.strip()
    if m := _STUDY_RE.match(raw):
        return _parse_study(m.group(1))
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    low = raw.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    if m := _QUANTITY_RE.match(raw):
        num, unit = m.groups()
        if unit in _UNIT_SCALE:
            return float(num) * _UNIT_SCALE[unit]
    return raw  # bare string (module type names etc.)


def _parse_study(body: str) -> Study:
    name = None
    if "=" in body and not body.lstrip().startswith(".."):
        head, body = body.split("=", 1)
        name = head.strip()
    body = body.strip()
    m = re.match(r"^(.+?)\.\.(.+?)(?:\s+step\s+(.+))?$", body)
    if m and "," not in body:
        lo, hi = parse_value(m.group(1)), parse_value(m.group(2))
        step = parse_value(m.group(3)) if m.group(3) else 1
        vals, v = [], lo
        while v <= hi + (1e-12 if isinstance(v, float) else 0):
            vals.append(v)
            v += step
        return Study(name, tuple(vals))
    return Study(name, tuple(parse_value(x) for x in body.split(",")))


def _pattern_to_regex(pattern: str) -> re.Pattern:
    """OMNeT++ wildcard pattern → regex over dot-separated paths."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if pattern.startswith("**", i):
            out.append(r".*")
            i += 2
        elif c == "*":
            out.append(r"[^.]*")
            i += 1
        elif c in ".[]{}()+^$|\\?":
            out.append("\\" + c)
            i += 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.compile("^" + "".join(out) + "$")


class IniFile:
    """Parsed ini tree: sections hold ordered (pattern, value) assignments."""

    def __init__(self):
        self.sections: dict[str, list[tuple[str, object]]] = {"General": []}
        self.extends: dict[str, str | None] = {"General": None}
        self._regex_cache: dict[str, re.Pattern] = {}
        self.base_dir = Path(".")   # for ini-relative resources (xml pools)

    # -- loading ------------------------------------------------------------

    @classmethod
    def load(cls, path: str | Path) -> "IniFile":
        ini = cls()
        ini._load_file(Path(path))
        return ini

    @classmethod
    def loads(cls, text: str, base_dir: str | Path = ".") -> "IniFile":
        ini = cls()
        ini._parse(text, Path(base_dir))
        return ini

    def _load_file(self, path: Path):
        self.base_dir = Path(path).parent
        self._parse(path.read_text(), path.parent)

    @staticmethod
    def _strip_comment(raw_line: str) -> str:
        """Drop a '#' comment, but only outside double-quoted strings
        (quoted values may legitimately contain '#')."""
        in_quote = False
        for i, ch in enumerate(raw_line):
            if ch == '"':
                in_quote = not in_quote
            elif ch == "#" and not in_quote:
                return raw_line[:i]
        return raw_line

    def _parse(self, text: str, base_dir: Path):
        current = "General"
        for raw_line in text.splitlines():
            line = self._strip_comment(raw_line).strip()
            if not line:
                continue
            # whole-word match: keys like 'includeTraffic = x' are plain
            # assignments, not include directives
            if re.match(r"^include\s", line):
                inc = line.split(None, 1)[1].strip()
                self._load_file(base_dir / inc)
                continue
            if line.startswith("["):
                name = line.strip("[]").strip()
                if name.startswith("Config "):
                    name = name[len("Config "):].strip()
                current = name
                self.sections.setdefault(current, [])
                self.extends.setdefault(
                    current, None if current == "General" else "General")
                continue
            if "=" not in line:
                continue
            key, val = line.split("=", 1)
            key, val = key.strip(), val.strip()
            if key == "extends":
                self.extends[current] = val.strip('"')
                continue
            self.sections.setdefault(current, []).append(
                (key, parse_value(val)))

    # -- resolution ---------------------------------------------------------

    def _chain(self, config: str):
        seen = []
        cur: str | None = config
        while cur is not None and cur not in seen:
            if cur in self.sections:
                seen.append(cur)
            cur = self.extends.get(cur, "General" if cur != "General" else None)
        if "General" not in seen and "General" in self.sections:
            seen.append("General")
        return seen

    def _match(self, pattern: str, path: str) -> bool:
        rx = self._regex_cache.get(pattern)
        if rx is None:
            rx = self._regex_cache[pattern] = _pattern_to_regex(pattern)
        return rx.match(path) is not None

    def get(self, path: str, config: str = "General", default=None):
        """Resolve a full parameter path (e.g.
        ``OverSim.overlayTerminal[3].overlay.chord.stabilizeDelay``) the
        OMNeT++ way: first matching assignment, config chain order."""
        for section in self._chain(config):
            for pattern, value in self.sections[section]:
                if self._match(pattern, path):
                    return value
        return default

    def study_variables(self, config: str = "General") -> dict[str, Study]:
        """All ${...} study placeholders reachable from ``config``."""
        out = {}
        for section in self._chain(config):
            for pattern, value in self.sections[section]:
                if isinstance(value, Study):
                    out.setdefault(value.name or pattern, value)
        return out

    def configs(self):
        return [s for s in self.sections if s != "General"]

    def with_overrides(self, config: str, pairs: dict[str, object]) -> str:
        """Create a derived config section holding ``pairs`` as highest-
        priority assignments; returns its name.  Used to pin one variant
        of a parameter study."""
        name = config
        i = 0
        while name in self.sections:
            i += 1
            name = f"{config}#{i}"
        self.sections[name] = list(pairs.items())
        self.extends[name] = config
        return name

    def expand_study_runs(self, config: str = "General"):
        """Expand ``${...}`` parameter studies into the cartesian product
        of run variants (OMNeT++ run expansion, thesis.ini:16).

        Yields (label, config_name) pairs; each config_name is a derived
        section pinning one combination under the study's original
        pattern key.  With no studies, yields the plain config once.
        """
        import itertools

        entries: list[tuple[str, Study]] = []
        seen = set()
        for section in self._chain(config):
            for pattern, value in self.sections[section]:
                if isinstance(value, Study):
                    key = value.name or pattern
                    if key not in seen:
                        seen.add(key)
                        entries.append((pattern, value))
        if not entries:
            yield "", config
            return
        for combo in itertools.product(*(s.values for _, s in entries)):
            label = ",".join(f"{s.name or p}={v}"
                             for (p, s), v in zip(entries, combo))
            pairs = {p: v for (p, _), v in zip(entries, combo)}
            yield label, self.with_overrides(config, pairs)
