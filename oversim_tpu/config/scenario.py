"""Scenario builder: resolved .ini parameters → a runnable Simulation.

The reference wires a simulation from string-configured module types
(``**.overlayType = "oversim.overlay.chord.ChordModules"``,
``**.tier1Type = "...KBRTestAppModules"``, churnGeneratorTypes —
simulations/default.ini:622-628) plus per-module parameter namespaces.
This module is the equivalent factory: it reads the same namespaces off an
`IniFile` and instantiates the engine's typed params / logic objects, so a
reference config runs against the TPU backend unchanged.
"""

from __future__ import annotations

import dataclasses

from oversim_tpu import churn as churn_mod
from oversim_tpu.apps import kbrtest
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.config.ini import IniFile, Study
from oversim_tpu.core import keys as K
from oversim_tpu.engine import sim as sim_mod
from oversim_tpu.underlay import simple as underlay_mod

HOST = "OverSim.overlayTerminal[0]"   # representative node path


def _value(x, default=None):
    if isinstance(x, Study):
        x = x.default()
    return default if x is None else x


class ScenarioError(ValueError):
    pass


def resolve_inbox_impl(value: str, *, available: bool | None = None,
                       warn: bool = True) -> str:
    """Resolve a raw ``**.inboxImpl`` string to the impl the engine runs.

    - ``"scatter"`` — the zero-sort scatter-min default.
    - ``"pallas"`` — the fused kernel plane (oversim_tpu/kernels/).
      Falls back to ``"scatter"`` with a stderr note when the plane is
      unimportable (``available`` overrides the probe for tests/pins).
    - ``"sort"`` — ORACLE-ONLY legacy full-pool sort; selecting it
      outside the test tier prints a stderr deprecation warning
      (suppressed under pytest and with ``warn=False``).

    Anything else raises :class:`ScenarioError`.
    """
    import os
    import sys

    impl = str(value).strip().strip('"')
    if impl not in ("scatter", "sort", "pallas"):
        raise ScenarioError(f"unsupported inboxImpl: {impl!r} "
                            "(expected \"scatter\", \"pallas\" or "
                            "\"sort\")")
    quiet = not warn or "PYTEST_CURRENT_TEST" in os.environ
    if impl == "pallas":
        if available is None:
            from oversim_tpu import kernels
            available = kernels.available()
        if not available:
            if not quiet:
                print("oversim-tpu: inboxImpl \"pallas\" requested but "
                      "the kernel plane is unavailable (no "
                      "jax.experimental.pallas) — falling back to "
                      "\"scatter\"", file=sys.stderr)
            return "scatter"
    elif impl == "sort" and not quiet:
        print("oversim-tpu: inboxImpl \"sort\" is deprecated and "
              "oracle-only — it exists to pin the scatter/pallas paths "
              "bit-identical in tests, not to run simulations; use "
              "\"scatter\" (default) or \"pallas\" (kernel plane)",
              file=sys.stderr)
    return impl


def resolve_tick_impl(value: str) -> str:
    """Resolve a raw ``**.tickImpl`` string to the tick plane the engine
    runs — ``"dense"`` (the full-N vmapped sweep, the bit-identity
    oracle) or ``"sparse"`` (the active-set plane: only awake nodes run
    the logic step; engine/sim.py ``_step_sparse``).  Both planes are
    pure-lax with Pallas variants, so there is no availability fallback
    to resolve; anything else raises :class:`ScenarioError`."""
    impl = str(value).strip().strip('"')
    if impl not in ("dense", "sparse"):
        raise ScenarioError(f"unsupported tickImpl: {impl!r} "
                            "(expected \"dense\" or \"sparse\")")
    return impl


def _get(ini, config, suffix, default=None):
    return _value(ini.get(f"{HOST}.{suffix}", config), default)


def build_churn(ini: IniFile, config: str) -> churn_mod.ChurnParams:
    gen = str(ini.get("OverSim.churnGenerator[0].__type__", config)
              or _value(ini.get("**.churnGeneratorTypes", config),
                        "oversim.common.NoChurn"))
    target = int(_value(ini.get("**.targetOverlayTerminalNum", config), 10))
    init_interval = float(_value(
        ini.get("**.initPhaseCreationInterval", config), 0.1))
    model = ("lifetime" if "LifetimeChurn" in gen
             else "pareto" if "ParetoChurn" in gen
             else "random" if "RandomChurn" in gen
             else "none")
    kw = {}
    if model in ("lifetime", "pareto"):
        kw["lifetime_mean"] = float(_value(
            ini.get("**.lifetimeMean", config), 10000.0))
        dist = str(_value(ini.get("**.lifetimeDistName", config), "weibull"))
        kw["lifetime_dist"] = dist
        kw["lifetime_par1"] = float(_value(
            ini.get("**.lifetimeDistPar1", config), 1.0))
    if model == "pareto":
        dm = ini.get("**.deadtimeMean", config)
        if dm is not None:
            kw["deadtime_mean"] = float(_value(dm))
    return churn_mod.ChurnParams(
        model=model, target_num=target, init_interval=init_interval, **kw)


def build_underlay(ini: IniFile, config: str):
    """(params, module) — the ``network`` line picks the underlay family
    (reference default.ini:16 SimpleUnderlayNetwork vs omnetpp.ini
    InetUnderlayNetwork/ReaSEUnderlayNetwork configs)."""
    net = str(_value(ini.get("network", config), "")).lower()
    if "inet" in net or "rease" in net:
        from oversim_tpu.underlay import inet as inet_mod
        params = inet_mod.InetUnderlayParams(
            topology="rease" if "rease" in net else "inet",
            routers=int(_value(
                ini.get("**.accessRouterNum", config), 16)),
            send_queue_bytes=int(_value(
                ini.get("**.sendQueueLength", config), 1_000_000)),
        )
        return params, inet_mod
    coord_src = str(_value(
        ini.get("**.nodeCoordinateSource", config), "")).strip('"')
    if coord_src:
        import os as _os
        if not _os.path.isabs(coord_src):
            coord_src = str(ini.base_dir / coord_src)
    params = underlay_mod.UnderlayParams(
        coord_source=coord_src,
        field_size=float(_value(ini.get("**.fieldSize", config), 150.0)),
        send_queue_bytes=int(_value(
            ini.get("**.sendQueueLength", config), 1_000_000)),
        constant_delay=float(_value(
            ini.get("**.constantDelay", config), 0.050)),
        use_coordinate_based_delay=bool(_value(
            ini.get("**.useCoordinateBasedDelay", config), True)),
    )
    return params, underlay_mod


def _build_dht(ini, config, spec, trace):
    from oversim_tpu.apps.dht import DhtApp, DhtParams
    return DhtApp(DhtParams(
        num_replica=int(_get(ini, config, "tier1.dht.numReplica", 4)),
        num_get_requests=int(_get(
            ini, config, "tier1.dht.numGetRequests", 4)),
        ratio_identical=float(_get(
            ini, config, "tier1.dht.ratioIdentical", 0.5)),
        test_interval=float(_get(
            ini, config, "tier2.dhtTestApp.testInterval", 60.0)),
        test_ttl=float(_get(
            ini, config, "tier2.dhtTestApp.testTtl", 300.0)),
    ), spec, trace=trace)


def _build_kbrtest(ini, config, spec, trace):
    from oversim_tpu.apps.kbrtest import KbrTestApp
    return KbrTestApp(kbrtest.KbrTestParams(
        test_interval=float(_get(
            ini, config, "tier1.kbrTestApp.testMsgInterval", 60.0)),
        test_msg_bytes=int(_get(
            ini, config, "tier1.kbrTestApp.testMsgSize", 100)),
        oneway_test=bool(_get(
            ini, config, "tier1.kbrTestApp.kbrOneWayTest", True)),
        rpc_test=bool(_get(
            ini, config, "tier1.kbrTestApp.kbrRpcTest", False)),
        lookup_test=bool(_get(
            ini, config, "tier1.kbrTestApp.kbrLookupTest", False)),
    ))


def _build_scribe(ini, config, spec, trace):
    from oversim_tpu.apps.scribe import ScribeApp, ScribeParams
    return ScribeApp(ScribeParams(
        num_groups=int(_get(ini, config, "tier2.almTest.groupNum", 4)),
    ), spec)


def _build_simmud(ini, config, spec, trace):
    from oversim_tpu.apps.simmud import SimMudApp, SimMudParams
    return SimMudApp(SimMudParams(), spec)


def _build_i3(ini, config, spec, trace):
    from oversim_tpu.apps.i3 import I3App
    return I3App(spec=spec)


def _build_p2pns(ini, config, spec, trace):
    from oversim_tpu.apps.p2pns import P2pnsApp
    return P2pnsApp(spec=spec)


def _build_ntree_app(ini, config, spec, trace):
    from oversim_tpu.apps.ntree import NTreeApp
    return NTreeApp(spec=spec)


def _build_broadcast(ini, config, spec, trace):
    from oversim_tpu.apps.broadcast import BroadcastTestApp
    return BroadcastTestApp()


def _build_dummy(ini, config, spec, trace):
    from oversim_tpu.apps.dummy import TierDummyApp
    return TierDummyApp()


# substring → factory; ordered (first match wins); entries absorbing a
# second tier list the partner substrings to consume
_TIER_FACTORIES = (
    ("KBRTestApp", _build_kbrtest, ()),
    ("DHTTestApp", _build_dht, ("DHT",)),      # tier2 naming the pair
    ("DHT", _build_dht, ("DHTTestApp",)),      # tier1 DHT + tier2 tester
    ("SimMud", _build_simmud, ("Scribe",)),
    ("Scribe", _build_scribe, ("ALMTest",)),
    ("ALMTest", _build_scribe, ("Scribe",)),
    ("I3", _build_i3, ()),
    ("P2pns", _build_p2pns, ()),
    ("P2PNS", _build_p2pns, ()),
    ("NTree", _build_ntree_app, ()),
    ("Broadcast", _build_broadcast, ()),
    ("TierDummy", _build_dummy, ()),
    ("MyApplication", _build_dummy, ()),
)


def build_app(ini: IniFile, config: str, spec: K.KeySpec, trace=None):
    """tier1Type/tier2Type/tier3Type strings → app object (reference
    default.ini:622-628 ITier plugin selection, SimpleOverlayHost.ned:
    14-100).  Multiple distinct tier apps compose into a generic
    :class:`~oversim_tpu.apps.stack.TierStack`; pairs the rebuild fuses
    into one object (DHT+DHTTestApp, Scribe+ALMTest) count as one tier.
    ``trace`` is an optional trace.TraceWorkload for trace-driven DHT
    runs (forces a DHT tier like the reference's trace manager)."""
    tiers = [str(_value(ini.get(f"**.tier{i}Type", config), ""))
             for i in (1, 2, 3)]
    # pre-scan ALL tiers before absorbing: the reference orders fused
    # pairs both ways (tier1 DHT + tier2 DHTTestApp, but tier1 Scribe +
    # tier2 SimMud), so first-match-wins in tier order would build both
    # halves of a pair
    matched = []
    for tname in tiers:
        if not tname or tname in ("\"\"",):
            continue
        for sub, factory, absorbs in _TIER_FACTORIES:
            if sub in tname:
                matched.append((sub, factory, absorbs))
                break
        # XmlRpcInterface (tier3) is the host-side gateway surface
        # (xmlrpcif.py over gateway.py), not an in-sim tier — ignored
        # here like the reference's GUI-only modules
    # fused pairs hitting the same factory collapse to one instance
    uniq, seen_fac = [], set()
    for sub, factory, absorbs in matched:
        if factory not in seen_fac:
            uniq.append((sub, factory, absorbs))
            seen_fac.add(factory)
    # an entry another surviving entry absorbs is that entry's lower
    # half (Scribe under SimMud) — drop it
    apps = [factory(ini, config, spec, trace)
            for sub, factory, absorbs in uniq
            if not any(sub in o[2] for o in uniq if o[1] is not factory)]
    if trace is not None and not any(
            type(a).__name__ == "DhtApp" for a in apps):
        apps.insert(0, _build_dht(ini, config, spec, trace))
    if not apps:
        return _build_kbrtest(ini, config, spec, trace)
    if len(apps) == 1:
        return apps[0]
    from oversim_tpu.apps.stack import TierStack
    return TierStack(apps)


def build_malicious(ini: IniFile, config: str):
    """maliciousNodeProbability + attack switches (default.ini:529-536,
    BaseOverlay.h:203-206) → MaliciousParams."""
    from oversim_tpu.common.malicious import MaliciousParams
    return MaliciousParams(
        probability=float(_value(
            ini.get("**.maliciousNodeProbability", config), 0.0)),
        drop_find_node=bool(_get(
            ini, config, "overlay.dropFindNodeAttack", False)),
        is_sibling=bool(_get(
            ini, config, "overlay.isSiblingAttack", False)),
        invalid_nodes=bool(_get(
            ini, config, "overlay.invalidNodesAttack", False)),
    )


def build_lookup_config(ini: IniFile, config: str, proto: str,
                        merge_default: bool) -> lk_mod.LookupConfig:
    ns = f"overlay.{proto}"
    paths = int(_get(ini, config, f"{ns}.lookupParallelPaths", 1))
    rpcs = int(_get(ini, config, f"{ns}.lookupParallelRpcs", 1))
    rt = str(_value(ini.get("**.routingType", config),
                    "iterative")).strip('"')
    return lk_mod.LookupConfig(
        merge=bool(_get(ini, config, f"{ns}.lookupMerge", merge_default)),
        # reference tracks paths as separate objects sharing one visited
        # set (IterativeLookup.cc:529); the vectorized engine expresses
        # paths x rpcs as total in-flight width R (lookup.py docstring)
        parallel_rpcs=max(1, paths * rpcs),
        # per-RPC re-send count.  The reference passes retries as a
        # lookup() call argument (AbstractLookup.h), not an ini param
        # (lookupFailedNodeRpcs is the unrelated failed-node-notice
        # bool) — `lookupRetries` is this framework's ini extension
        retries=int(_get(ini, config, f"{ns}.lookupRetries", 0)),
        exhaustive=rt == "exhaustive-iterative",
        # PROX_AWARE_ITERATIVE_ROUTING (CommonMessages.msg:140; enum-only
        # in the reference — implemented here, lookup.py prox_aware)
        prox_aware=rt == "prox-aware-iterative",
        rpc_timeout_ns=int(float(_value(
            ini.get("**.rpcUdpTimeout", config), 1.5)) * 1e9),
    )


def build_telemetry(ini: IniFile, config: str):
    """``**.telemetry.*`` keys → TelemetryParams (framework ini
    extension — the device-resident KPI time-series plane,
    oversim_tpu/telemetry.py):

      **.telemetry.sampleTicks = 16       snapshot cadence (0 = off)
      **.telemetry.window      = 256      ring-buffer capacity W
      **.telemetry.include     = "kbr_hopcount kbr_hop_hist"
                                          substring tap filter (optional;
                                          overrides the app's kpi_spec)
    """
    from oversim_tpu import telemetry as telemetry_mod
    sample_ticks = int(_value(
        ini.get("**.telemetry.sampleTicks", config), 0))
    if sample_ticks < 0:
        raise ScenarioError(f"**.telemetry.sampleTicks must be >= 0, "
                            f"got {sample_ticks}")
    window = int(_value(ini.get("**.telemetry.window", config), 256))
    if sample_ticks > 0 and window < 1:
        raise ScenarioError(f"**.telemetry.window must be >= 1, "
                            f"got {window}")
    raw = _value(ini.get("**.telemetry.include", config), "")
    include = tuple(str(raw).strip().strip('"').replace(",", " ").split())
    return telemetry_mod.TelemetryParams(
        sample_ticks=sample_ticks, window=window, include=include)


def build_simulation(ini: IniFile, config: str = "General",
                     engine_params: sim_mod.EngineParams | None = None,
                     trace_events=None):
    """Instantiate the full Simulation for one [Config ...] section.

    ``trace_events``: parsed trace.TraceEvent list — overrides the churn
    model with the trace schedule, drives the DHT workload from PUT/GET
    commands, and applies CONNECT/DISCONNECT_NODETYPES partitions
    (reference GlobalTraceManager)."""
    overlay_type = str(_value(ini.get("**.overlayType", config), ""))
    spec = K.KeySpec(int(_value(ini.get("**.keyLength", config), 160)))
    up, ul_mod = build_underlay(ini, config)
    workload = None
    if trace_events is not None:
        from oversim_tpu import trace as trace_mod
        cp = trace_mod.churn_from_trace(trace_events)
        workload = trace_mod.workload_from_trace(trace_events, cp.num_slots,
                                                 spec)
        ps = trace_mod.partitions_from_trace(trace_events)
        if len(ps.t):
            ntypes = int(max(ps.a.max(), ps.b.max())) + 1
            bounds = tuple(cp.num_slots * i // ntypes
                           for i in range(1, ntypes))
            up = dataclasses.replace(
                up, num_node_types=ntypes, type_boundaries=bounds,
                partition_events=tuple(
                    (float(t), int(a), int(b), bool(c))
                    for t, a, b, c in zip(ps.t, ps.a, ps.b, ps.connect)))
    else:
        cp = build_churn(ini, config)
    ap = build_app(ini, config, spec, trace=workload)
    mp = build_malicious(ini, config)
    inbox_impl = resolve_inbox_impl(_value(
        ini.get("**.inboxImpl", config), "scatter"))
    tick_impl = resolve_tick_impl(_value(
        ini.get("**.tickImpl", config), "dense"))
    ep = engine_params or sim_mod.EngineParams(
        transition_time=float(_value(
            ini.get("**.transitionTime", config), 0.0)),
        measurement_time=float(_value(
            ini.get("**.measurementTime", config), -1.0)),
        # **.inboxImpl: inbox grouping algorithm — "scatter" (zero-sort
        # scatter-min rounds, default) | "pallas" (fused kernel plane,
        # oversim_tpu/kernels/) | "sort" (ORACLE-ONLY legacy full-pool
        # sort); this framework's ini extension, engine/pool.py
        inbox_impl=inbox_impl,
        # **.tickImpl: "dense" (full-N oracle, default) | "sparse"
        # (active-set plane); **.activeCap bounds the sparse lane count
        # (0 = auto) — this framework's ini extension, engine/sim.py
        tick_impl=tick_impl,
        active_cap=int(_value(ini.get("**.activeCap", config), 0)),
        malicious=mp,
        telemetry=build_telemetry(ini, config),
    )

    if "chord" in overlay_type.lower():
        from oversim_tpu.overlay.chord import ChordLogic, ChordParams
        params = ChordParams(
            join_delay=float(_get(ini, config, "overlay.chord.joinDelay",
                                  10.0)),
            stabilize_delay=float(_get(
                ini, config, "overlay.chord.stabilizeDelay", 20.0)),
            fixfingers_delay=float(_get(
                ini, config, "overlay.chord.fixfingersDelay", 120.0)),
            check_pred_delay=float(_get(
                ini, config, "overlay.chord.checkPredecessorDelay", 5.0)),
            succ_size=int(_get(
                ini, config, "overlay.chord.successorListSize", 8)),
            aggressive_join=bool(_get(
                ini, config, "overlay.chord.aggressiveJoinMode", True)),
        )
        logic = ChordLogic(spec, params,
                           build_lookup_config(ini, config, "chord", False),
                           ap, mparams=mp)
    elif "kademlia" in overlay_type.lower():
        from oversim_tpu.overlay.kademlia import (KademliaLogic,
                                                  KademliaParams)
        params = KademliaParams(
            k=int(_get(ini, config, "overlay.kademlia.k", 8)),
            s=int(_get(ini, config, "overlay.kademlia.s", 8)),
            max_stale=int(_get(
                ini, config, "overlay.kademlia.maxStaleCount", 0)),
            sibling_refresh=float(_get(
                ini, config,
                "overlay.kademlia.minSiblingTableRefreshInterval", 1000.0)),
            bucket_refresh=float(_get(
                ini, config,
                "overlay.kademlia.minBucketRefreshInterval", 1000.0)),
            redundant_nodes=int(_get(
                ini, config, "overlay.kademlia.lookupRedundantNodes", 8)),
        )
        logic = KademliaLogic(spec, params,
                              build_lookup_config(ini, config, "kademlia",
                                                  True), ap, mparams=mp)
    elif "pastry" in overlay_type.lower() or "bamboo" in overlay_type.lower():
        from oversim_tpu.overlay.pastry import (BambooLogic, PastryLogic,
                                                PastryParams)
        proto = ("bamboo" if "bamboo" in overlay_type.lower() else "pastry")
        params = PastryParams(
            bits_per_digit=int(_get(
                ini, config, f"overlay.{proto}.bitsPerDigit", 4)),
            num_leaves=int(_get(
                ini, config, f"overlay.{proto}.numberOfLeaves",
                8 if proto == "bamboo" else 16)),
            join_delay=int(_get(
                ini, config, f"overlay.{proto}.joinTimeout", 20)),
        )
        cls = BambooLogic if proto == "bamboo" else PastryLogic
        logic = cls(spec, params,
                    build_lookup_config(ini, config, proto, False), ap)
    elif "koorde" in overlay_type.lower():
        from oversim_tpu.overlay.koorde import KoordeLogic, KoordeParams
        params = KoordeParams(
            stabilize_delay=float(_get(
                ini, config, "overlay.koorde.stabilizeDelay", 10.0)),
            succ_size=int(_get(
                ini, config, "overlay.koorde.successorListSize", 16)),
            de_bruijn_delay=float(_get(
                ini, config, "overlay.koorde.deBruijnDelay", 30.0)),
            de_bruijn_size=int(_get(
                ini, config, "overlay.koorde.deBruijnListSize", 16)),
            shifting_bits=int(_get(
                ini, config, "overlay.koorde.shiftingBits", 4)),
        )
        logic = KoordeLogic(spec, params, app=ap)
    elif "broose" in overlay_type.lower():
        from oversim_tpu.overlay.broose import BrooseLogic, BrooseParams
        params = BrooseParams(
            bucket_size=int(_get(
                ini, config, "overlay.broose.bucketSize", 8)),
            r_bucket_size=int(_get(
                ini, config, "overlay.broose.rBucketSize", 8)),
            shifting_bits=int(_value(
                ini.get("**.brooseShiftingBits", config), 2)),
            join_delay=float(_get(
                ini, config, "overlay.broose.joinDelay", 10.0)),
            refresh_time=float(_get(
                ini, config, "overlay.broose.refreshTime", 180.0)),
        )
        logic = BrooseLogic(spec, params, app=ap)
    elif "epichord" in overlay_type.lower():
        from oversim_tpu.overlay.epichord import (EpiChordLogic,
                                                  EpiChordParams)
        params = EpiChordParams(
            succ_size=int(_get(
                ini, config, "overlay.epichord.successorListSize", 4)),
            join_delay=float(_get(
                ini, config, "overlay.epichord.joinDelay", 10.0)),
            stabilize_delay=float(_get(
                ini, config, "overlay.epichord.stabilizeDelay", 20.0)),
            cache_flush_delay=float(_get(
                ini, config, "overlay.epichord.cacheFlushDelay", 20.0)),
            cache_check_mult=int(_get(
                ini, config, "overlay.epichord.cacheCheckMultiplier", 3)),
            cache_ttl=float(_get(
                ini, config, "overlay.epichord.cacheTTL", 120.0)),
            nodes_per_slice=int(_get(
                ini, config, "overlay.epichord.nodesPerSlice", 2)),
            redundant_nodes=int(_get(
                ini, config, "overlay.epichord.lookupRedundantNodes", 3)),
        )
        logic = EpiChordLogic(spec, params,
                              build_lookup_config(ini, config, "epichord",
                                                  True), ap)
    elif "gia" in overlay_type.lower():
        from oversim_tpu.overlay.gia import GiaLogic, GiaParams
        params = GiaParams(
            min_neighbors=int(_get(
                ini, config, "overlay.gia.minNeighbors", 3)),
            max_neighbors=int(_get(
                ini, config, "overlay.gia.maxNeighbors", 10)),
            adapt_interval=float(_get(
                ini, config, "overlay.gia.maxTopAdaptionInterval", 10.0)),
            search_ttl=int(_get(
                ini, config, "overlay.gia.maxHopCount", 20)),
            max_responses=int(_get(
                ini, config, "overlay.gia.maxResponses", 1)),
            token_wait=float(_get(
                ini, config, "overlay.gia.tokenWaitTime", 1.0)),
        )
        logic = GiaLogic(spec, params)
    elif "nice" in overlay_type.lower():
        from oversim_tpu.overlay.nice import NiceLogic, NiceParams
        params = NiceParams(
            k=int(_get(ini, config, "overlay.nice.k", 3)),
            hb_interval=float(_get(
                ini, config, "overlay.nice.heartbeatInterval", 5.0)),
            maint_interval=float(_get(
                ini, config, "overlay.nice.maintenanceInterval", 3.3)),
            query_interval=float(_get(
                ini, config, "overlay.nice.queryInterval", 2.0)),
            peer_timeout_hbs=float(_get(
                ini, config, "overlay.nice.peerTimeoutHeartbeats", 3.0)),
        )
        logic = NiceLogic(spec, params)
    elif "quon" in overlay_type.lower():
        from oversim_tpu.overlay.quon import QuonLogic, QuonParams
        params = QuonParams(
            aoi=float(_get(ini, config, "overlay.quon.AOIWidth", 100.0)),
        )
        logic = QuonLogic(spec, params)
    elif "vast" in overlay_type.lower():
        from oversim_tpu.overlay.vast import VastLogic, VastParams
        params = VastParams(
            aoi=float(_get(ini, config, "overlay.vast.AOIWidth", 100.0)),
        )
        logic = VastLogic(spec, params)
    elif "ntree" in overlay_type.lower():
        # NTree runs as a tier app over a KBR overlay here (rendezvous-
        # hashed cell leadership; apps/ntree.py docstring) — the
        # reference's NTreeModules overlay maps to Chord + NTreeApp
        from oversim_tpu.apps.ntree import NTreeApp, NTreeParams
        from oversim_tpu.overlay.chord import ChordLogic
        ap = NTreeApp(NTreeParams(
            max_children=int(_value(
                ini.get("**.maxChildren", config), 5))), spec=spec)
        logic = ChordLogic(spec, app=ap)
    elif "pubsub" in overlay_type.lower():
        from oversim_tpu.overlay.pubsubmmog import (PubSubMMOGLogic,
                                                    PubSubParams)
        params = PubSubParams(
            field=float(_get(
                ini, config, "overlay.pubsubmmog.areaDimension", 1000.0)),
            grid=int(_get(
                ini, config, "overlay.pubsubmmog.numSubspaces", 4)),
            aoi=float(_get(
                ini, config, "overlay.pubsubmmog.AOIWidth", 100.0)),
            move_rate=float(_get(
                ini, config, "overlay.pubsubmmog.movementRate", 2.0)),
            parent_timeout=float(_get(
                ini, config, "overlay.pubsubmmog.parentTimeout", 2.0)),
            max_move_delay=float(_get(
                ini, config, "overlay.pubsubmmog.maxMoveDelay", 1.0)),
            max_children=int(_get(
                ini, config, "overlay.pubsubmmog.maxChildren", 12)),
        )
        logic = PubSubMMOGLogic(spec, params)
    else:
        raise ScenarioError(f"unsupported overlayType: {overlay_type!r}")

    return sim_mod.Simulation(logic, cp, up, ep, underlay_module=ul_mod)


# -- campaign (multi-replica) configuration ---------------------------------
#
# Framework ini extension (no reference equivalent — the reference runs
# repetitions as separate ./OverSim -r N processes):
#
#   **.campaign.replicas  = 8            seed replicas per grid point
#   **.campaign.baseSeed  = 1            replica r rng = fold_in(seed, r)
#   **.campaign.sweep.lifetimeMean    = "5000 10000 20000"
#   **.campaign.sweep.testMsgInterval = "10, 60"
#   **.campaign.sweep.window          = "0.05 0.1"
#
# Sweep values are space/comma-separated (quotes optional); declared
# axes form a cartesian grid, total replicas S = replicas × grid size.

_SWEEP_KEYS = (
    ("**.campaign.sweep.lifetimeMean", "churn.lifetimeMean"),
    ("**.campaign.sweep.testMsgInterval", "app.testMsgInterval"),
    ("**.campaign.sweep.window", "engine.window"),
)


def _sweep_values(raw, key):
    s = str(raw).strip().strip('"')
    try:
        vals = tuple(float(x) for x in s.replace(",", " ").split())
    except ValueError:
        vals = ()
    if not vals:
        raise ScenarioError(f"bad sweep value list for {key}: {raw!r}")
    return vals


def build_campaign_params(ini: IniFile, config: str = "General"):
    """``**.campaign.*`` keys → CampaignParams (see the comment above)."""
    from oversim_tpu.campaign import CampaignParams
    replicas = int(_value(ini.get("**.campaign.replicas", config), 1))
    if replicas < 1:
        raise ScenarioError(f"**.campaign.replicas must be >= 1, "
                            f"got {replicas}")
    base_seed = int(_value(ini.get("**.campaign.baseSeed", config), 1))
    sweep = []
    for ini_key, ov_name in _SWEEP_KEYS:
        raw = _value(ini.get(ini_key, config))
        if raw is None:
            continue
        sweep.append((ov_name, _sweep_values(raw, ini_key)))
    return CampaignParams(replicas=replicas, base_seed=base_seed,
                          sweep=tuple(sweep))


def build_campaign(ini: IniFile, config: str = "General",
                   engine_params: sim_mod.EngineParams | None = None,
                   trace_events=None):
    """build_simulation + ``**.campaign.*`` keys → a Campaign driver."""
    from oversim_tpu.campaign import Campaign
    sim = build_simulation(ini, config, engine_params=engine_params,
                           trace_events=trace_events)
    return Campaign(sim, build_campaign_params(ini, config))


def build_service(ini: IniFile, config: str = "General"):
    """``**.service.*`` keys → ServiceParams (framework ini extension —
    the resident serving loop, oversim_tpu/service/):

      **.service.windowSimS      = 1.0    simulated seconds per window
      **.service.chunk           = 32     ticks per device scan chunk
      **.service.checkpointEvery = 0      windows between checkpoints
      **.service.checkpointPath  = "x.npz"
      **.service.maxWindows      = 0      absolute window count (0 = ∞)
      **.service.maxWallS        = 0      wall budget per run() (0 = ∞)
      **.service.doubleBuffer    = true   pipeline fetch k / dispatch k+1
      **.service.realtime        = false  pace windows to wall clock
    """
    from oversim_tpu.service import ServiceParams
    window_sim_s = float(_value(
        ini.get("**.service.windowSimS", config), 1.0))
    if window_sim_s <= 0:
        raise ScenarioError(f"**.service.windowSimS must be > 0, "
                            f"got {window_sim_s}")
    chunk = int(_value(ini.get("**.service.chunk", config), 32))
    if chunk < 1:
        raise ScenarioError(f"**.service.chunk must be >= 1, got {chunk}")
    ckpt_every = int(_value(
        ini.get("**.service.checkpointEvery", config), 0))
    if ckpt_every < 0:
        raise ScenarioError(f"**.service.checkpointEvery must be >= 0, "
                            f"got {ckpt_every}")
    raw_path = _value(ini.get("**.service.checkpointPath", config))
    ckpt_path = (None if raw_path is None
                 else str(raw_path).strip().strip('"') or None)
    if ckpt_every > 0 and ckpt_path is None:
        raise ScenarioError("**.service.checkpointEvery set without a "
                            "**.service.checkpointPath")
    max_windows = int(_value(ini.get("**.service.maxWindows", config), 0))
    if max_windows < 0:
        raise ScenarioError(f"**.service.maxWindows must be >= 0, "
                            f"got {max_windows}")
    max_wall_s = float(_value(ini.get("**.service.maxWallS", config), 0.0))
    dbuf = bool(_value(ini.get("**.service.doubleBuffer", config), True))
    realtime = bool(_value(ini.get("**.service.realtime", config), False))
    return ServiceParams(
        window_sim_s=window_sim_s, chunk=chunk,
        checkpoint_every=ckpt_every, checkpoint_path=ckpt_path,
        max_windows=max_windows, max_wall_s=max_wall_s,
        double_buffer=dbuf, realtime=realtime)
