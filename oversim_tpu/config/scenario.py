"""Scenario builder: resolved .ini parameters → a runnable Simulation.

The reference wires a simulation from string-configured module types
(``**.overlayType = "oversim.overlay.chord.ChordModules"``,
``**.tier1Type = "...KBRTestAppModules"``, churnGeneratorTypes —
simulations/default.ini:622-628) plus per-module parameter namespaces.
This module is the equivalent factory: it reads the same namespaces off an
`IniFile` and instantiates the engine's typed params / logic objects, so a
reference config runs against the TPU backend unchanged.
"""

from __future__ import annotations

from oversim_tpu import churn as churn_mod
from oversim_tpu.apps import kbrtest
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.config.ini import IniFile, Study
from oversim_tpu.core import keys as K
from oversim_tpu.engine import sim as sim_mod
from oversim_tpu.underlay import simple as underlay_mod

HOST = "OverSim.overlayTerminal[0]"   # representative node path


def _value(x, default=None):
    if isinstance(x, Study):
        x = x.default()
    return default if x is None else x


class ScenarioError(ValueError):
    pass


def _get(ini, config, suffix, default=None):
    return _value(ini.get(f"{HOST}.{suffix}", config), default)


def build_churn(ini: IniFile, config: str) -> churn_mod.ChurnParams:
    gen = str(ini.get("OverSim.churnGenerator[0].__type__", config)
              or _value(ini.get("**.churnGeneratorTypes", config),
                        "oversim.common.NoChurn"))
    target = int(_value(ini.get("**.targetOverlayTerminalNum", config), 10))
    init_interval = float(_value(
        ini.get("**.initPhaseCreationInterval", config), 0.1))
    model = ("lifetime" if "LifetimeChurn" in gen
             else "pareto" if "ParetoChurn" in gen
             else "random" if "RandomChurn" in gen
             else "none")
    kw = {}
    if model in ("lifetime", "pareto"):
        kw["lifetime_mean"] = float(_value(
            ini.get("**.lifetimeMean", config), 10000.0))
        dist = str(_value(ini.get("**.lifetimeDistName", config), "weibull"))
        kw["lifetime_dist"] = dist
        kw["lifetime_par1"] = float(_value(
            ini.get("**.lifetimeDistPar1", config), 1.0))
    if model == "pareto":
        dm = ini.get("**.deadtimeMean", config)
        if dm is not None:
            kw["deadtime_mean"] = float(_value(dm))
    return churn_mod.ChurnParams(
        model=model, target_num=target, init_interval=init_interval, **kw)


def build_underlay(ini: IniFile, config: str) -> underlay_mod.UnderlayParams:
    return underlay_mod.UnderlayParams(
        field_size=float(_value(ini.get("**.fieldSize", config), 150.0)),
        send_queue_bytes=int(_value(
            ini.get("**.sendQueueLength", config), 1_000_000)),
        constant_delay=float(_value(
            ini.get("**.constantDelay", config), 0.050)),
        use_coordinate_based_delay=bool(_value(
            ini.get("**.useCoordinateBasedDelay", config), True)),
    )


def build_app(ini: IniFile, config: str, spec: K.KeySpec):
    """tier1Type/tier2Type string → app object (reference default.ini:622-628
    module-type plugin selection)."""
    t1 = str(_value(ini.get("**.tier1Type", config), ""))
    t2 = str(_value(ini.get("**.tier2Type", config), ""))
    if "DHT" in t1 or "DHTTestApp" in t2:
        from oversim_tpu.apps.dht import DhtApp, DhtParams
        return DhtApp(DhtParams(
            num_replica=int(_get(ini, config, "tier1.dht.numReplica", 4)),
            test_interval=float(_get(
                ini, config, "tier2.dhtTestApp.testInterval", 60.0)),
            test_ttl=float(_get(
                ini, config, "tier2.dhtTestApp.testTtl", 300.0)),
        ), spec)
    from oversim_tpu.apps.kbrtest import KbrTestApp
    return KbrTestApp(kbrtest.KbrTestParams(
        test_interval=float(_get(
            ini, config, "tier1.kbrTestApp.testMsgInterval", 60.0)),
        test_msg_bytes=int(_get(
            ini, config, "tier1.kbrTestApp.testMsgSize", 100)),
    ))


def build_lookup_config(ini: IniFile, config: str, proto: str,
                        merge_default: bool) -> lk_mod.LookupConfig:
    ns = f"overlay.{proto}"
    return lk_mod.LookupConfig(
        merge=bool(_get(ini, config, f"{ns}.lookupMerge", merge_default)),
        rpc_timeout_ns=int(float(_value(
            ini.get("**.rpcUdpTimeout", config), 1.5)) * 1e9),
    )


def build_simulation(ini: IniFile, config: str = "General",
                     engine_params: sim_mod.EngineParams | None = None):
    """Instantiate the full Simulation for one [Config ...] section."""
    overlay_type = str(_value(ini.get("**.overlayType", config), ""))
    spec = K.KeySpec(int(_value(ini.get("**.keyLength", config), 160)))
    cp = build_churn(ini, config)
    up = build_underlay(ini, config)
    ap = build_app(ini, config, spec)
    ep = engine_params or sim_mod.EngineParams(
        transition_time=float(_value(
            ini.get("**.transitionTime", config), 0.0)),
        measurement_time=float(_value(
            ini.get("**.measurementTime", config), -1.0)),
    )

    if "chord" in overlay_type.lower():
        from oversim_tpu.overlay.chord import ChordLogic, ChordParams
        params = ChordParams(
            join_delay=float(_get(ini, config, "overlay.chord.joinDelay",
                                  10.0)),
            stabilize_delay=float(_get(
                ini, config, "overlay.chord.stabilizeDelay", 20.0)),
            fixfingers_delay=float(_get(
                ini, config, "overlay.chord.fixfingersDelay", 120.0)),
            check_pred_delay=float(_get(
                ini, config, "overlay.chord.checkPredecessorDelay", 5.0)),
            succ_size=int(_get(
                ini, config, "overlay.chord.successorListSize", 8)),
            aggressive_join=bool(_get(
                ini, config, "overlay.chord.aggressiveJoinMode", True)),
        )
        logic = ChordLogic(spec, params,
                           build_lookup_config(ini, config, "chord", False),
                           ap)
    elif "kademlia" in overlay_type.lower():
        from oversim_tpu.overlay.kademlia import (KademliaLogic,
                                                  KademliaParams)
        params = KademliaParams(
            k=int(_get(ini, config, "overlay.kademlia.k", 8)),
            s=int(_get(ini, config, "overlay.kademlia.s", 8)),
            max_stale=int(_get(
                ini, config, "overlay.kademlia.maxStaleCount", 0)),
            sibling_refresh=float(_get(
                ini, config,
                "overlay.kademlia.minSiblingTableRefreshInterval", 1000.0)),
            bucket_refresh=float(_get(
                ini, config,
                "overlay.kademlia.minBucketRefreshInterval", 1000.0)),
            redundant_nodes=int(_get(
                ini, config, "overlay.kademlia.lookupRedundantNodes", 8)),
        )
        logic = KademliaLogic(spec, params,
                              build_lookup_config(ini, config, "kademlia",
                                                  True), ap)
    elif "pastry" in overlay_type.lower() or "bamboo" in overlay_type.lower():
        from oversim_tpu.overlay.pastry import (BambooLogic, PastryLogic,
                                                PastryParams)
        proto = ("bamboo" if "bamboo" in overlay_type.lower() else "pastry")
        params = PastryParams(
            bits_per_digit=int(_get(
                ini, config, f"overlay.{proto}.bitsPerDigit", 4)),
            num_leaves=int(_get(
                ini, config, f"overlay.{proto}.numberOfLeaves",
                8 if proto == "bamboo" else 16)),
            join_delay=int(_get(
                ini, config, f"overlay.{proto}.joinTimeout", 20)),
        )
        cls = BambooLogic if proto == "bamboo" else PastryLogic
        logic = cls(spec, params,
                    build_lookup_config(ini, config, proto, False), ap)
    else:
        raise ScenarioError(f"unsupported overlayType: {overlay_type!r}")

    return sim_mod.Simulation(logic, cp, up, ep)
