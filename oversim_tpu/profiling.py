"""Per-phase tick profiling — OVERSIM_PROFILE=1 (PERFORMANCE.md lever).

The tick graph is op-issue/compile-bound and opaque: when a bench run
dies or posts a bad number, nothing says WHICH of the tick's phases ate
the time (the round-5 bench artifact was a deadline-killed 0.0 with no
diagnosis).  This module times the phases of ``Simulation.step``
(engine/sim.py splits them exactly for this):

  horizon       event-horizon scan + rng split
  churn         churn events, alive flips, key/coord migration, resets
  inbox_select  due-message top-R selection (scatter-min rounds by
                default; the legacy full-pool sort under
                inbox_impl="sort")
  inbox_gather  packed-block gather of the selected messages → Msg view
  node_step     tick context + the vmapped per-node logic sweep
  alloc_stats   underlay send, sort-free pool alloc, stat folding

Under the kernel plane (``inbox_impl="pallas"``) selection and gather
are ONE fused Pallas kernel — the report then carries a single
``inbox_fused`` phase in their place (plus ``kernel_plane: true``)
instead of silently attributing the kernel time to neither half.

Under the sparse plane (``tick_impl="sparse"``) the layout is
``horizon / churn / inbox_select / active_compact / sparse_step /
alloc_stats``: selection never gathers the full payload block,
``active_compact`` packs the awake set into A lanes, and
``sparse_step`` is the logic sweep over those lanes only (the report
carries ``tick_impl`` so artifact readers can tell the layouts apart).

Each phase is jitted SEPARATELY and timed with ``block_until_ready``
over ``n_ticks`` real ticks.  Sub-jits lose cross-phase fusion, so the
phase sum exceeds the fused tick cost — the per-phase SHARES are the
diagnostic signal, and the fused cost is measured alongside via
``run_chunk`` for the honest denominator.  The report also carries
``sort_count`` / ``scatter_count`` pinned-op counts off the fused
compiled tick (scripts/hlo_breakdown.py counting rules), so a lever
regression (a sort sneaking back into the hot path) shows up in every
profiled bench artifact.

Usage:
    from oversim_tpu import profiling
    if profiling.enabled():
        report, s = profiling.profile_ticks(sim, s, n_ticks=4)
        print(json.dumps(report))

``bench.py``, ``scripts/perf_probe.py`` and ``scripts/scale_smoke.py``
emit the report as a JSON line when OVERSIM_PROFILE=1.
"""

from __future__ import annotations

import os
import time

import jax

PHASES = ("horizon", "churn", "inbox_select", "inbox_gather", "node_step",
          "alloc_stats")
# kernel-plane layout: the fused Pallas kernel owns both inbox halves
PHASES_FUSED = ("horizon", "churn", "inbox_fused", "node_step",
                "alloc_stats")
# sparse-plane layout (tick_impl="sparse"): selection never gathers the
# full [N, R, W] payload; the awake set compacts into A lanes
# (active_compact) and only those lanes run the logic sweep (sparse_step)
PHASES_SPARSE = ("horizon", "churn", "inbox_select", "active_compact",
                 "sparse_step", "alloc_stats")


def phases_for(inbox_impl: str, tick_impl: str = "dense") -> tuple:
    """The phase layout a Simulation's tick decomposes into."""
    if tick_impl == "sparse":
        return PHASES_SPARSE
    return PHASES_FUSED if inbox_impl == "pallas" else PHASES


def enabled() -> bool:
    """True when OVERSIM_PROFILE is set to a non-empty, non-"0" value."""
    return os.environ.get("OVERSIM_PROFILE", "") not in ("", "0")


def _jit_phases(sim):
    """Jit the phase methods of a Simulation (closures keep ``sim``
    static, mirroring run_chunk's static ``self``)."""
    return {
        "horizon": jax.jit(
            lambda s: sim._phase_horizon(s)),
        "churn": jax.jit(
            lambda s, tn, te, rc, rk, rr, rm: sim._phase_churn(
                s, tn, te, rc, rk, rr, rm)),
        "inbox_select": jax.jit(
            lambda s, te, alive: sim._phase_inbox_select(s, te, alive)),
        "inbox_gather": jax.jit(
            lambda s, tn, inbox: sim._phase_inbox_gather(s, tn, inbox)),
        "inbox_fused": jax.jit(
            lambda s, tn, te, alive: sim._phase_inbox_fused(
                s, tn, te, alive)),
        "node_step": jax.jit(
            lambda s, tn, te, alive, pk, cs, nk, ul, lg, msgs, rn:
            sim._phase_node_step(s, tn, te, alive, pk, cs, nk, ul, lg,
                                 msgs, rn)),
        "alloc_stats": jax.jit(
            lambda s, te, rng, rs, alive, pk, nk, ul, cs, lg, dlv, dead,
            of, ov, oo, ev, ms: sim._phase_alloc_stats(
                s, te, rng, rs, alive, pk, nk, ul, cs, lg, dlv, dead,
                of, ov, oo, ev, ms)),
        # sparse plane (tick_impl="sparse")
        "inbox_select_sparse": jax.jit(
            lambda s, te, alive: sim._phase_inbox_select_sparse(
                s, te, alive)),
        "active_compact": jax.jit(
            lambda s, te, alive, pk, lg, inbox, dlv:
            sim._phase_active_compact(s, te, alive, pk, lg, inbox, dlv)),
        "sparse_step": jax.jit(
            lambda s, tn, te, alive, pk, cs, nk, ul, lg, inbox, act, rn:
            sim._phase_sparse_step(s, tn, te, alive, pk, cs, nk, ul, lg,
                                   inbox, act, rn)),
        "alloc_stats_sparse": jax.jit(
            lambda s, te, rng, rs, alive, pk, nk, ul, cs, lg, dlv, dead,
            of, ov, oo, ev, ms, act: sim._phase_alloc_stats(
                s, te, rng, rs, alive, pk, nk, ul, cs, lg, dlv, dead,
                of, ov, oo, ev, ms, active=act)),
    }


def tick_op_counts(sim, s) -> dict:
    """sort/scatter pinned-op counts off the FUSED compiled tick.

    Compiles ``jit(sim.step)`` (cache-shared with run_chunk's scan body
    where the backend persists compilations) and applies the
    scripts/hlo_breakdown.py counting rules.  Returns {} when the
    backend does not expose compiled HLO text (some tunnel plugins).
    """
    try:
        from scripts.hlo_breakdown import hlo_op_counts
        txt = jax.jit(sim.step).lower(s).compile().as_text()
        return hlo_op_counts(txt, sim.ep.pool_factor * sim.n)
    except Exception:  # noqa: BLE001 — diagnostics must never kill a bench
        return {}


def profile_ticks(sim, s, n_ticks: int = 4, fused_reference: bool = True,
                  op_counts: bool = True):
    """Run ``n_ticks`` real ticks phase by phase, timing each phase.

    Returns ``(report, s)`` — the report dict (JSON-serializable) and
    the advanced SimState (the profiled ticks are real simulation
    progress; callers keep using the returned state).  The first tick
    pays all phase compiles and is EXCLUDED from the averages.
    """
    fns = _jit_phases(sim)
    sparse = sim.ep.tick_impl == "sparse"
    fused_inbox = sim.ep.inbox_impl == "pallas" and not sparse
    phases = phases_for(sim.ep.inbox_impl, sim.ep.tick_impl)
    totals = {p: 0.0 for p in phases}
    compile_s = 0.0
    measured = 0
    tick_rows = []    # per measured tick: {phase: ms} — Perfetto feed

    for tick in range(n_ticks + 1):
        first = tick == 0
        t_tick0 = time.perf_counter()

        t0 = time.perf_counter()
        t_next, t_end, rngs = jax.block_until_ready(
            fns["horizon"](s))
        dt_h = time.perf_counter() - t0
        (rng, r_churn, r_keys, r_reset, r_nodes, r_mig, r_send) = rngs

        t0 = time.perf_counter()
        (churn_state, alive, pre_killed, node_keys, ul_state,
         logic_state) = jax.block_until_ready(
            fns["churn"](s, t_next, t_end, r_churn, r_keys, r_reset, r_mig))
        dt_c = time.perf_counter() - t0

        if sparse:
            t0 = time.perf_counter()
            inbox, delivered, to_dead = jax.block_until_ready(
                fns["inbox_select_sparse"](s, t_end, alive))
            dt_is = time.perf_counter() - t0

            t0 = time.perf_counter()
            act, delivered, active = jax.block_until_ready(
                fns["active_compact"](s, t_end, alive, pre_killed,
                                      logic_state, inbox, delivered))
            inbox_dts = (dt_is, time.perf_counter() - t0)

            t0 = time.perf_counter()
            (logic_state, out_fields, out_valid, out_overflow, events,
             measuring) = jax.block_until_ready(
                fns["sparse_step"](s, t_next, t_end, alive, pre_killed,
                                   churn_state, node_keys, ul_state,
                                   logic_state, inbox, act, r_nodes))
            dt_n = time.perf_counter() - t0

            t0 = time.perf_counter()
            s = jax.block_until_ready(
                fns["alloc_stats_sparse"](
                    s, t_end, rng, r_send, alive, pre_killed, node_keys,
                    ul_state, churn_state, logic_state, delivered, to_dead,
                    out_fields, out_valid, out_overflow, events, measuring,
                    active))
            dt_a = time.perf_counter() - t0
        else:
            if fused_inbox:
                t0 = time.perf_counter()
                msgs, delivered, to_dead = jax.block_until_ready(
                    fns["inbox_fused"](s, t_next, t_end, alive))
                inbox_dts = (time.perf_counter() - t0,)
            else:
                t0 = time.perf_counter()
                inbox, delivered, to_dead = jax.block_until_ready(
                    fns["inbox_select"](s, t_end, alive))
                dt_is = time.perf_counter() - t0

                t0 = time.perf_counter()
                msgs = jax.block_until_ready(
                    fns["inbox_gather"](s, t_next, inbox))
                inbox_dts = (dt_is, time.perf_counter() - t0)

            t0 = time.perf_counter()
            (logic_state, out_fields, out_valid, out_overflow, events,
             measuring) = jax.block_until_ready(
                fns["node_step"](s, t_next, t_end, alive, pre_killed,
                                 churn_state, node_keys, ul_state,
                                 logic_state, msgs, r_nodes))
            dt_n = time.perf_counter() - t0

            t0 = time.perf_counter()
            s = jax.block_until_ready(
                fns["alloc_stats"](s, t_end, rng, r_send, alive, pre_killed,
                                   node_keys, ul_state, churn_state,
                                   logic_state, delivered, to_dead,
                                   out_fields, out_valid, out_overflow,
                                   events, measuring))
            dt_a = time.perf_counter() - t0

        if first:
            compile_s = time.perf_counter() - t_tick0
            continue
        measured += 1
        row = {}
        for p, dt in zip(phases, (dt_h, dt_c, *inbox_dts, dt_n, dt_a)):
            totals[p] += dt
            row[p] = round(dt * 1e3, 3)
        tick_rows.append(row)

    denom = max(measured, 1)
    phase_ms = {p: round(totals[p] / denom * 1e3, 3) for p in phases}
    split_sum = sum(totals.values()) / denom
    report = {
        "metric": "tick_phase_breakdown",
        "n_ticks": measured,
        "inbox_impl": sim.ep.inbox_impl,
        "tick_impl": sim.ep.tick_impl,
        "kernel_plane": fused_inbox,
        "phase_ms_per_tick": phase_ms,
        "phase_frac": {p: round(totals[p] / max(sum(totals.values()), 1e-12),
                                4) for p in phases},
        "split_sum_ms_per_tick": round(split_sum * 1e3, 3),
        # per-tick phase rows (ms) — telemetry.PerfettoTrace.add_profile
        # lays them out as back-to-back tick.<phase> spans
        "phase_ticks_ms": tick_rows,
        "phase_compile_s": round(compile_s, 2),
    }

    if op_counts:
        report.update(tick_op_counts(sim, s))

    if fused_reference:
        # fused cost via run_chunk (donating; rebind s both times).  The
        # first call may compile — time only the second.
        s = jax.block_until_ready(sim.run_chunk(s, n_ticks))
        t0 = time.perf_counter()
        s = jax.block_until_ready(sim.run_chunk(s, n_ticks))
        fused = (time.perf_counter() - t0) / max(n_ticks, 1)
        report["fused_ms_per_tick"] = round(fused * 1e3, 3)
        report["split_overhead_x"] = round(split_sum / max(fused, 1e-12), 2)

    return report, s
