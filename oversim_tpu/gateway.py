"""Real-network gateway: the SingleHostUnderlay equivalent.

The reference's singlehostunderlay (src/underlay/singlehostunderlay/:
SingleHostUnderlayConfigurator + realtimescheduler.h:38-163) runs ONE
overlay node whose UDP/TUN gates are wired to the real network, paced
by a realtime scheduler so simulated time tracks wall-clock time.

The TPU rebuild keeps the whole simulated overlay and bridges a chosen
*gateway node slot* to real sockets instead:

  * inbound datagrams are injected into the message pool as ``EXT_IN``
    messages addressed to the gateway slot (pool.alloc, the same path
    the underlay writes its outbox with — the reference's message
    parsers live in singlehostunderlay/*messageparser*);
  * any ``EXT_OUT`` message a node sends to the gateway slot is
    intercepted after the tick, serialized and transmitted to the real
    peer it answers (matched by the ext-session nonce);
  * ``run_realtime`` steps the simulation so that simulated time never
    runs ahead of wall-clock time (realtimescheduler.cc: the scheduler
    blocks on the socket until the next event is due, here a
    poll+sleep loop with the same bound).

UDP datagrams map 1:1 onto messages.  TCP connections (the reference's
SimpleTCP / TCPExampleApp path) are framed by a 4-byte big-endian
length prefix; each frame becomes one ``EXT_IN`` message and each
``EXT_OUT`` reply one frame, so a sim app serves real TCP clients.

Wire format of an external frame (network byte order):
    u32 kind_tag | u32 a | u32 b | u32 c | payload bytes (≤ key width)
"""

from __future__ import annotations

import dataclasses
import errno
import socket
import struct
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from oversim_tpu.engine import pool as pool_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
NO_NODE = jnp.int32(-1)

EXT_IN = 150    # real network → gateway node (a=session, b=tag, c=word)
EXT_OUT = 151   # gateway node → real network (same fields echoed)
EXT_NACK = 152  # gateway → real network: frame SHED by admission control

_HDR = struct.Struct("!IIII")

# a 4-byte length prefix larger than this means the TCP byte stream is
# desynced (garbage where a prefix should be): the connection can never
# produce a complete frame again and is dropped
_MAX_TCP_FRAME = 1 << 20


class GenericPacketParser:
    """Pluggable wire codec between real packets and sim messages.

    Rebuild of the reference's GenericPacketParser
    (src/common/GenericPacketParser.{h,cc}: ``decapsulatePayload(buf,
    length) -> cPacket`` / ``encapsulatePayload(msg) -> buf``, selected
    per underlay via the ``parserType`` NED parameter and used by the
    singlehost message parsers).  The gateway calls ``decapsulate`` on
    every received datagram/TCP frame and ``encapsulate`` on every
    outbound EXT_OUT message — subclass both to speak any external
    protocol (the default implements the framework's native
    ``u32 kind | a | b | c`` header)."""

    def decapsulate(self, data: bytes):
        """bytes → (b, c) payload words, or None to drop the packet."""
        if len(data) < _HDR.size:
            return None
        _, _, b, c = _HDR.unpack_from(data)
        return b, c

    def encapsulate(self, sid: int, b: int, c: int) -> bytes:
        """EXT_OUT message fields → wire bytes."""
        return _HDR.pack(EXT_OUT, sid & 0xFFFFFFFF, b & 0xFFFFFFFF,
                         c & 0xFFFFFFFF)

    def nack(self, sid: int, b: int, c: int) -> bytes:
        """Explicit shed notice: the frame was received, parsed, and
        REFUSED by admission control — the peer can retry later instead
        of waiting on a reply that will never come."""
        return _HDR.pack(EXT_NACK, sid & 0xFFFFFFFF, b & 0xFFFFFFFF,
                         c & 0xFFFFFFFF)


def drain_ext_out(state, gw_slot: int, handler):
    """Scan the pool for EXT_OUT messages addressed to ``gw_slot`` and
    offer each to ``handler(sid, b, c) -> consumed``; free exactly the
    consumed slots.  The ONE drain implementation shared by the socket
    gateway and the TUN bridge (their session kinds partition the sid
    space via the handler predicate)."""
    pool = state.pool
    valid = np.asarray(pool.valid)
    kind = np.asarray(pool.kind)
    dst = np.asarray(pool.dst)
    hits = np.nonzero(valid & (kind == EXT_OUT) & (dst == gw_slot))[0]
    if len(hits) == 0:
        return state
    a = np.asarray(pool.a)
    b = np.asarray(pool.b)
    c = np.asarray(pool.c)
    done = [int(i) for i in hits
            if handler(int(a[i]), int(b[i]), int(c[i]))]
    if not done:
        return state
    mask = jnp.zeros(pool.valid.shape, bool).at[
        jnp.asarray(done, I32)].set(True)
    return dataclasses.replace(state, pool=pool_mod.free(pool, mask))


@dataclasses.dataclass
class ExtFrame:
    """One externally arriving frame awaiting batched injection."""

    a: int = 0
    b: int = 0
    c: int = 0
    kind: int = EXT_IN
    dst: int | None = None
    src: int | None = None
    key: object = None       # uint32 key lanes, or None for zeros


def inject_ext_batch(state, frames, gw_slot: int, t_deliver=None):
    """Write ``frames`` into the pool as ONE batched alloc.

    The per-packet ``inject`` path costs one ``pool.alloc`` dispatch per
    datagram; a service window boundary injects the whole accumulated
    batch in a single allocation instead.  All frames share one deliver
    time — the next tick (``t_now + 1``) by default, or ``t_deliver``
    (absolute ns; the service loop schedules its batch into the
    window's final tick) — in list order (pool.alloc's cumsum ranking
    preserves batch order among equal ``t_deliver``).

    Returns ``(state', overflow)`` where ``overflow`` is the alloc's
    device scalar of frames that did NOT fit in the pool — kept as a lazy
    handle so callers on the service hot path don't force a host sync;
    ``None`` when ``frames`` is empty (state returned unchanged).
    """
    if not frames:
        return state, None
    n = len(frames)
    rmax = state.pool.nodes.shape[1]
    lanes = state.pool.key.shape[1]
    key_rows = np.zeros((n, lanes), np.uint32)
    for i, f in enumerate(frames):
        if f.key is not None:
            key_rows[i] = np.asarray(f.key, np.uint32)
    when = (state.t_now + 1 if t_deliver is None
            else jnp.maximum(jnp.asarray(t_deliver, I64), state.t_now + 1))
    out = dict(
        t_deliver=jnp.broadcast_to(when, (n,)).astype(I64),
        src=jnp.asarray([gw_slot if f.src is None else f.src
                         for f in frames], I32),
        dst=jnp.asarray([gw_slot if f.dst is None else f.dst
                         for f in frames], I32),
        kind=jnp.asarray([f.kind for f in frames], I32),
        key=jnp.asarray(key_rows),
        nonce=jnp.zeros((n,), I32),
        hops=jnp.zeros((n,), I32),
        a=jnp.asarray([f.a for f in frames], I32),
        b=jnp.asarray([f.b for f in frames], I32),
        c=jnp.asarray([f.c for f in frames], I32),
        d=jnp.zeros((n,), I32),
        nodes=jnp.full((n, rmax), NO_NODE, I32),
        size_b=jnp.full((n,), _HDR.size, I32),
        stamp=jnp.broadcast_to(state.t_now, (n,)).astype(I64),
    )
    new_pool, overflow = pool_mod.alloc(state.pool, out,
                                        jnp.ones((n,), bool))
    return dataclasses.replace(state, pool=new_pool), overflow


class RealtimeGateway:
    """Bridges one simulation node slot to real UDP/TCP sockets."""

    def __init__(self, sim, state, gw_slot: int = 0,
                 udp_port: int = 0, tcp_port: int | None = None,
                 host: str = "127.0.0.1",
                 stun_server: tuple | None = None,
                 crypto=None, parser: GenericPacketParser | None = None,
                 tracer=None, max_rx_backlog: int | None = None):
        self.sim = sim
        self.state = state
        self.gw = gw_slot
        # request tracing (duck-typed obs.RequestTracer: mint/settle per
        # sid) — a plain parameter so this module never imports obs; the
        # gateway has no window index, so latencies here are wall-only
        self.tracer = tracer
        # pluggable wire codec (GenericPacketParser.h parserType)
        self.parser = parser or GenericPacketParser()
        # real-signature path (common/crypto.py CryptoModule — the
        # reference signs RPC messages with the keyFile key in
        # SingleHost mode, CryptoModule.h:56): every outbound frame is
        # signed, every inbound frame must carry a valid auth block
        self.crypto = crypto
        # extra between-tick drains (TunBridge registers here): EXT_OUT
        # messages a drain does not consume would be DELIVERED back into
        # the gateway node's inbox on the next tick and lost
        self.ext_drains: list = []
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind((host, udp_port))
        self.udp.setblocking(False)
        self.udp_port = self.udp.getsockname()[1]
        # STUN bootstrap (SingleHostUnderlayConfigurator.cc:108-134 —
        # **.stunServer learns the public address before joining): the
        # binding request goes out the OVERLAY's own UDP socket so the
        # reflexive address maps this very port.  public_addr falls
        # back to the local bind when no server is given/reachable.
        self.public_addr = (host, self.udp_port)
        self.nat_detected = False
        if stun_server is not None:
            from oversim_tpu import singlehost as _sh
            mapped = _sh.stun_discover(self.udp, stun_server)
            if mapped is not None:
                self.public_addr = mapped
                # NAT is only attributable when the bind address is a
                # concrete interface IP — a wildcard bind has no local
                # address to compare the reflexive one against
                self.nat_detected = (host not in ("0.0.0.0", "::", "")
                                     and mapped != (host, self.udp_port))
        self.tcp = None
        self.tcp_port = None
        self._tcp_conns: dict = {}      # session id -> (sock, rx buffer)
        # per-connection WRITE buffers: outbound frames are appended
        # (prefix+payload, atomically) and drained with non-blocking
        # send() on every poll — sendall on a non-blocking socket can
        # raise after a PARTIAL write, truncating the length-prefixed
        # stream mid-frame and desyncing the peer forever
        self._tcp_tx: dict = {}         # session id -> tx bytearray
        self.tx_partial_writes = 0      # sends the kernel only partly took
        if tcp_port is not None:
            self.tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self.tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self.tcp.bind((host, tcp_port))
            self.tcp.listen(8)
            self.tcp.setblocking(False)
            self.tcp_port = self.tcp.getsockname()[1]
        self._sessions: dict = {}       # session id -> (addr | conn key)
        self._next_session = 1
        self._seen_pool = None          # pool validity snapshot
        # RX hardening/batching state: frames accumulate host-side in
        # _rx and enter the pool as ONE alloc per flush_rx (the service
        # loop flushes at window boundaries, pump() per slice)
        self._rx: list = []
        self._rx_overflow: list = []    # lazy device scalars, see rx_overflow
        self.rx_frames = 0              # frames injected (post-parse)
        self.rx_batches = 0             # batched pool writes performed
        self.rx_dropped = 0             # malformed/unauthenticated frames
        self.rx_socket_errors = 0       # transient socket-level errors
        # admission control: once _rx holds this many pending frames,
        # further well-formed frames are SHED — counted, NACKed back to
        # the peer, never queued (bounded backlog keeps window latency
        # from growing without bound under overload).  None = unbounded.
        self.max_rx_backlog = max_rx_backlog
        self.rx_shed = 0                # frames refused by admission ctl
        self._warned: set = set()       # one stderr warning per category
        # serving-window index (set by service.ingest.GatewayIngest per
        # boundary) so traced latencies carry window units; None on the
        # per-tick pump/run_realtime path (wall-only, the old behavior)
        self._window = None

    # ------------------------------------------------ injection --------
    def inject(self, kind: int, a: int = 0, b: int = 0, c: int = 0,
               key=None, dst: int | None = None, src: int | None = None):
        """Write one message into the pool, delivered immediately."""
        self.state, _ = inject_ext_batch(
            self.state, [ExtFrame(a=a, b=b, c=c, kind=kind,
                                  dst=dst, src=src, key=key)], self.gw)

    def flush_rx(self, t_deliver=None):
        """Inject every accumulated RX frame as ONE batched pool write."""
        if not self._rx:
            return
        frames, self._rx = self._rx, []
        self.state, overflow = inject_ext_batch(self.state, frames,
                                                self.gw,
                                                t_deliver=t_deliver)
        self._rx_overflow.append(overflow)
        self.rx_batches += 1
        self.rx_frames += len(frames)

    def rx_overflow(self) -> int:
        """Frames lost to pool overflow across all flushed batches.

        The per-batch overflow counts stay on device as lazy scalars
        (an ``int()`` right after ``flush_rx`` would force a host sync
        on the service hot path); summing here blocks on them."""
        total = sum(int(np.asarray(h)) for h in self._rx_overflow)
        self._rx_overflow = [np.int64(total)] if total else []
        return total

    # ------------------------------------------------ socket pumps -----
    def _rx_warn(self, category: str, detail: str):
        """One stderr warning per error category; the rx_* counters
        count every occurrence."""
        if category not in self._warned:
            self._warned.add(category)
            print(f"oversim-tpu gateway: dropping {category} ({detail});"
                  " counted in rx_dropped/rx_socket_errors, further"
                  " occurrences silent", file=sys.stderr)

    def _shed_frame(self, sid: int, b: int, c: int, transmit) -> None:
        """Refuse one admitted frame: count it, settle its trace as
        NACKed, and send the explicit NACK back via ``transmit`` —
        deterministic shedding, never a silent drop."""
        self.rx_shed += 1
        self._rx_warn(
            "shed frame (admission control)",
            f"rx backlog at max_rx_backlog={self.max_rx_backlog}")
        self._trace("nack", sid)
        payload = self.parser.nack(sid, b, c)
        if self.crypto is not None:
            payload = self.crypto.sign_frame(payload)
        try:
            transmit(payload)
        except OSError:
            pass

    def _decode_frame(self, data: bytes, what: str):
        """Verify + parse one frame; None (counted + warned) on ANY
        decode failure — one malformed packet from the real network
        must never unwind run_realtime."""
        try:
            if self.crypto is not None:
                data = self.crypto.verify_frame(data)
                if data is None:
                    self.rx_dropped += 1
                    self._rx_warn(f"unauthenticated {what}",
                                  "bad auth block")
                    return None
            parsed = self.parser.decapsulate(data)
            if parsed is None:
                self.rx_dropped += 1
                self._rx_warn(f"rejected {what}", "parser returned None")
                return None
            return parsed
        except Exception as e:  # noqa: BLE001 — any parser/crypto crash
            self.rx_dropped += 1
            self._rx_warn(f"malformed {what}", repr(e))
            return None

    def _trace(self, event: str, sid: int):
        """mint/settle/nack on the tracer, threading the serving-window
        index when the ingest adapter set one (window units make the
        latency histograms scale-free; the per-tick pump path keeps the
        old wall-only no-kwarg calls for duck-typed test tracers)."""
        if self.tracer is None:
            return
        fn = getattr(self.tracer, event, None)
        if fn is None:
            return
        if self._window is not None:
            fn(sid, window=self._window)
        else:
            fn(sid)

    def _send_tcp(self, sid: int, payload: bytes):
        """Queue one length-prefixed frame on the session's write
        buffer and drain opportunistically.  The append is atomic per
        frame, so concurrent frames can interleave only at frame
        boundaries — never mid-frame, even when the socket buffer is
        full (the partial-write audit, tests/test_gateway.py)."""
        if sid not in self._tcp_conns:
            return
        buf = self._tcp_tx.setdefault(sid, bytearray())
        buf += len(payload).to_bytes(4, "big") + payload
        self._pump_tx(sid)

    def _pump_tx(self, only_sid=None):
        """Drain pending per-connection write buffers with non-blocking
        sends; whatever the kernel refuses stays queued for the next
        poll.  A hard send error drops the buffer (the rx side notices
        the dead socket and reaps the session)."""
        sids = ((only_sid,) if only_sid is not None
                else tuple(self._tcp_tx))
        for sid in sids:
            buf = self._tcp_tx.get(sid)
            entry = self._tcp_conns.get(sid)
            if not buf or entry is None:
                if entry is None:
                    self._tcp_tx.pop(sid, None)
                continue
            conn = entry[0]
            while buf:
                try:
                    n = conn.send(buf)
                except BlockingIOError:
                    break
                except OSError:
                    self._tcp_tx.pop(sid, None)
                    break
                if n < len(buf):
                    self.tx_partial_writes += 1
                del buf[:n]

    def _poll_udp(self):
        socket_errs = 0
        while True:
            try:
                data, addr = self.udp.recvfrom(65536)
            except BlockingIOError:
                return
            except InterruptedError:
                continue
            except OSError as e:
                # an earlier sendto to a dead peer surfaces here as
                # ECONNREFUSED/ECONNRESET (ICMP port-unreachable): drop
                # it and keep draining the queue — bounded, so a truly
                # broken socket (e.g. EBADF) cannot spin forever
                self.rx_socket_errors += 1
                self._rx_warn("udp socket error", repr(e))
                socket_errs += 1
                if (e.errno in (errno.ECONNREFUSED, errno.ECONNRESET)
                        and socket_errs < 64):
                    continue
                return
            parsed = self._decode_frame(data, "udp datagram")
            if parsed is None:
                continue
            b, c = parsed
            sid = self._next_session
            self._next_session += 1
            self._trace("mint", sid)
            if (self.max_rx_backlog is not None
                    and len(self._rx) >= self.max_rx_backlog):
                # no session entry: a shed frame never gets an EXT_OUT
                self._shed_frame(
                    sid, b, c, lambda p: self.udp.sendto(p, addr))
                continue
            self._sessions[sid] = ("udp", addr)
            self._rx.append(ExtFrame(a=sid, b=b, c=c))

    def _poll_tcp(self):
        if self.tcp is None:
            return
        while True:
            try:
                conn, addr = self.tcp.accept()
            except (BlockingIOError, OSError):
                break
            conn.setblocking(False)
            sid = self._next_session
            self._next_session += 1
            self._tcp_conns[sid] = (conn, bytearray())
            self._sessions[sid] = ("tcp", sid)
        dead = []
        for sid, (conn, buf) in self._tcp_conns.items():
            try:
                chunk = conn.recv(65536)
                if chunk == b"":
                    dead.append(sid)
                    continue
                buf.extend(chunk)
            except BlockingIOError:
                pass
            except OSError as e:
                self.rx_socket_errors += 1
                self._rx_warn("tcp socket error", repr(e))
                dead.append(sid)
                continue
            # length-prefixed frames (SimpleTCP stream framing)
            while len(buf) >= 4:
                ln = int.from_bytes(buf[:4], "big")
                if ln > _MAX_TCP_FRAME:
                    # garbage where the prefix should be: the stream is
                    # desynced and would wait forever for a frame that
                    # never completes — the connection is unrecoverable
                    self.rx_dropped += 1
                    self._rx_warn("desynced tcp stream",
                                  f"length prefix {ln}")
                    dead.append(sid)
                    break
                if len(buf) < 4 + ln:
                    break             # incomplete frame: wait for more
                # undersized frames fall through to the parser, which
                # rejects them (custom parsers may use smaller framing)
                frame = bytes(buf[4:4 + ln])
                del buf[:4 + ln]
                parsed = self._decode_frame(frame, "tcp frame")
                if parsed is None:
                    continue
                b, c = parsed
                # per-FRAME mint on the per-connection sid: a fresh
                # request on a kept-alive stream re-opens the trace
                self._trace("mint", sid)
                if (self.max_rx_backlog is not None
                        and len(self._rx) >= self.max_rx_backlog):
                    # connection survives — only this frame is refused
                    self._shed_frame(
                        sid, b, c,
                        lambda p, _sid=sid: self._send_tcp(_sid, p))
                    continue
                self._rx.append(ExtFrame(a=sid, b=b, c=c))
        for sid in dead:
            self._tcp_conns.pop(sid, None)
            self._tcp_tx.pop(sid, None)
            self._sessions.pop(sid, None)
        self._pump_tx()

    def _drain_ext_out(self):
        """Transmit socket-session EXT_OUT messages (raw-packet/tun
        sessions drain via TunBridge.collect_raw — the shared
        :func:`drain_ext_out` frees only what its handler consumed)."""

        def handler(sid, b, c):
            sess = self._sessions.get(sid)
            if sess is not None and sess[0] == "tun":
                return False          # not ours — leave for the bridge
            if sess is None:
                return True           # orphan: free, nothing to send
            self._trace("settle", sid)
            payload = self.parser.encapsulate(sid, b, c)
            if self.crypto is not None:
                payload = self.crypto.sign_frame(payload)
            if sess[0] == "udp":
                try:
                    self.udp.sendto(payload, sess[1])
                except OSError:
                    pass
            else:
                self._send_tcp(sid, payload)
            return True

        self.state = drain_ext_out(self.state, self.gw, handler)

    # ------------------------------------------------ the loop ---------
    def pump(self, sim_seconds: float = 0.1):
        """Poll sockets, inject, advance the simulation, transmit.

        Steps tick by tick and drains EXT_OUT *between* ticks — an
        EXT_OUT self-send would otherwise be delivered back into the
        gateway node's inbox (and consumed) on the very next tick."""
        self._poll_udp()
        self._poll_tcp()
        self.flush_rx()
        target = int(self.state.t_now) + int(sim_seconds * NS)  # analysis: allow(device-sync)
        while int(self.state.t_now) < target:  # analysis: allow(device-sync)
            prev = int(self.state.t_now)  # analysis: allow(device-sync)
            self.state = self.sim.step(self.state)
            self._drain_ext_out()
            for fn in self.ext_drains:
                fn()
            if int(self.state.t_now) == prev and not bool(  # analysis: allow(device-sync)
                    np.asarray(self.state.pool.valid).any()):  # analysis: allow(device-sync)
                break   # nothing scheduled anywhere: idle sim

    def run_realtime(self, duration_s: float, slice_s: float = 0.05):
        """Realtime pacing: simulated time tracks wall-clock time
        (realtimescheduler.cc waits on the socket until the next event)."""
        t0_wall = time.monotonic()
        t0_sim = int(self.state.t_now) / NS  # analysis: allow(device-sync)
        while True:
            elapsed = time.monotonic() - t0_wall
            if elapsed >= duration_s:
                return
            ahead = (int(self.state.t_now) / NS - t0_sim) - elapsed  # analysis: allow(device-sync)
            if ahead > slice_s:
                time.sleep(min(ahead, slice_s))
                continue
            self.pump(slice_s)

    def close(self):
        self.udp.close()
        if self.tcp is not None:
            self.tcp.close()
        for conn, _ in self._tcp_conns.values():
            try:
                conn.close()
            except OSError:
                pass
