"""Real-network gateway: the SingleHostUnderlay equivalent.

The reference's singlehostunderlay (src/underlay/singlehostunderlay/:
SingleHostUnderlayConfigurator + realtimescheduler.h:38-163) runs ONE
overlay node whose UDP/TUN gates are wired to the real network, paced
by a realtime scheduler so simulated time tracks wall-clock time.

The TPU rebuild keeps the whole simulated overlay and bridges a chosen
*gateway node slot* to real sockets instead:

  * inbound datagrams are injected into the message pool as ``EXT_IN``
    messages addressed to the gateway slot (pool.alloc, the same path
    the underlay writes its outbox with — the reference's message
    parsers live in singlehostunderlay/*messageparser*);
  * any ``EXT_OUT`` message a node sends to the gateway slot is
    intercepted after the tick, serialized and transmitted to the real
    peer it answers (matched by the ext-session nonce);
  * ``run_realtime`` steps the simulation so that simulated time never
    runs ahead of wall-clock time (realtimescheduler.cc: the scheduler
    blocks on the socket until the next event is due, here a
    poll+sleep loop with the same bound).

UDP datagrams map 1:1 onto messages.  TCP connections (the reference's
SimpleTCP / TCPExampleApp path) are framed by a 4-byte big-endian
length prefix; each frame becomes one ``EXT_IN`` message and each
``EXT_OUT`` reply one frame, so a sim app serves real TCP clients.

Wire format of an external frame (network byte order):
    u32 kind_tag | u32 a | u32 b | u32 c | payload bytes (≤ key width)
"""

from __future__ import annotations

import dataclasses
import socket
import struct
import time

import jax
import jax.numpy as jnp
import numpy as np

from oversim_tpu.engine import pool as pool_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
NO_NODE = jnp.int32(-1)

EXT_IN = 150    # real network → gateway node (a=session, b=tag, c=word)
EXT_OUT = 151   # gateway node → real network (same fields echoed)

_HDR = struct.Struct("!IIII")


class GenericPacketParser:
    """Pluggable wire codec between real packets and sim messages.

    Rebuild of the reference's GenericPacketParser
    (src/common/GenericPacketParser.{h,cc}: ``decapsulatePayload(buf,
    length) -> cPacket`` / ``encapsulatePayload(msg) -> buf``, selected
    per underlay via the ``parserType`` NED parameter and used by the
    singlehost message parsers).  The gateway calls ``decapsulate`` on
    every received datagram/TCP frame and ``encapsulate`` on every
    outbound EXT_OUT message — subclass both to speak any external
    protocol (the default implements the framework's native
    ``u32 kind | a | b | c`` header)."""

    def decapsulate(self, data: bytes):
        """bytes → (b, c) payload words, or None to drop the packet."""
        if len(data) < _HDR.size:
            return None
        _, _, b, c = _HDR.unpack_from(data)
        return b, c

    def encapsulate(self, sid: int, b: int, c: int) -> bytes:
        """EXT_OUT message fields → wire bytes."""
        return _HDR.pack(EXT_OUT, sid & 0xFFFFFFFF, b & 0xFFFFFFFF,
                         c & 0xFFFFFFFF)


def drain_ext_out(state, gw_slot: int, handler):
    """Scan the pool for EXT_OUT messages addressed to ``gw_slot`` and
    offer each to ``handler(sid, b, c) -> consumed``; free exactly the
    consumed slots.  The ONE drain implementation shared by the socket
    gateway and the TUN bridge (their session kinds partition the sid
    space via the handler predicate)."""
    pool = state.pool
    valid = np.asarray(pool.valid)
    kind = np.asarray(pool.kind)
    dst = np.asarray(pool.dst)
    hits = np.nonzero(valid & (kind == EXT_OUT) & (dst == gw_slot))[0]
    if len(hits) == 0:
        return state
    a = np.asarray(pool.a)
    b = np.asarray(pool.b)
    c = np.asarray(pool.c)
    done = [int(i) for i in hits
            if handler(int(a[i]), int(b[i]), int(c[i]))]
    if not done:
        return state
    mask = jnp.zeros(pool.valid.shape, bool).at[
        jnp.asarray(done, I32)].set(True)
    return dataclasses.replace(state, pool=pool_mod.free(pool, mask))


class RealtimeGateway:
    """Bridges one simulation node slot to real UDP/TCP sockets."""

    def __init__(self, sim, state, gw_slot: int = 0,
                 udp_port: int = 0, tcp_port: int | None = None,
                 host: str = "127.0.0.1",
                 stun_server: tuple | None = None,
                 crypto=None, parser: GenericPacketParser | None = None):
        self.sim = sim
        self.state = state
        self.gw = gw_slot
        # pluggable wire codec (GenericPacketParser.h parserType)
        self.parser = parser or GenericPacketParser()
        # real-signature path (common/crypto.py CryptoModule — the
        # reference signs RPC messages with the keyFile key in
        # SingleHost mode, CryptoModule.h:56): every outbound frame is
        # signed, every inbound frame must carry a valid auth block
        self.crypto = crypto
        # extra between-tick drains (TunBridge registers here): EXT_OUT
        # messages a drain does not consume would be DELIVERED back into
        # the gateway node's inbox on the next tick and lost
        self.ext_drains: list = []
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind((host, udp_port))
        self.udp.setblocking(False)
        self.udp_port = self.udp.getsockname()[1]
        # STUN bootstrap (SingleHostUnderlayConfigurator.cc:108-134 —
        # **.stunServer learns the public address before joining): the
        # binding request goes out the OVERLAY's own UDP socket so the
        # reflexive address maps this very port.  public_addr falls
        # back to the local bind when no server is given/reachable.
        self.public_addr = (host, self.udp_port)
        self.nat_detected = False
        if stun_server is not None:
            from oversim_tpu import singlehost as _sh
            mapped = _sh.stun_discover(self.udp, stun_server)
            if mapped is not None:
                self.public_addr = mapped
                # NAT is only attributable when the bind address is a
                # concrete interface IP — a wildcard bind has no local
                # address to compare the reflexive one against
                self.nat_detected = (host not in ("0.0.0.0", "::", "")
                                     and mapped != (host, self.udp_port))
        self.tcp = None
        self.tcp_port = None
        self._tcp_conns: dict = {}      # session id -> (sock, rx buffer)
        if tcp_port is not None:
            self.tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self.tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self.tcp.bind((host, tcp_port))
            self.tcp.listen(8)
            self.tcp.setblocking(False)
            self.tcp_port = self.tcp.getsockname()[1]
        self._sessions: dict = {}       # session id -> (addr | conn key)
        self._next_session = 1
        self._seen_pool = None          # pool validity snapshot

    # ------------------------------------------------ injection --------
    def inject(self, kind: int, a: int = 0, b: int = 0, c: int = 0,
               key=None, dst: int | None = None, src: int | None = None):
        """Write one message into the pool, delivered immediately."""
        s = self.state
        rmax = s.pool.nodes.shape[1]
        lanes = s.pool.key.shape[1]
        out = dict(
            t_deliver=jnp.asarray([s.t_now + 1], I64),
            src=jnp.asarray([self.gw if src is None else src], I32),
            dst=jnp.asarray([self.gw if dst is None else dst], I32),
            kind=jnp.asarray([kind], I32),
            key=(jnp.zeros((1, lanes), jnp.uint32) if key is None
                 else jnp.asarray(key, jnp.uint32)[None, :]),
            nonce=jnp.zeros((1,), I32),
            hops=jnp.zeros((1,), I32),
            a=jnp.asarray([a], I32), b=jnp.asarray([b], I32),
            c=jnp.asarray([c], I32), d=jnp.zeros((1,), I32),
            nodes=jnp.full((1, rmax), NO_NODE, I32),
            size_b=jnp.asarray([_HDR.size], I32),
            stamp=jnp.asarray([s.t_now], I64),
        )
        new_pool, _ = pool_mod.alloc(s.pool, out, jnp.asarray([True]))
        self.state = dataclasses.replace(s, pool=new_pool)

    # ------------------------------------------------ socket pumps -----
    def _poll_udp(self):
        while True:
            try:
                data, addr = self.udp.recvfrom(65536)
            except BlockingIOError:
                return
            except OSError:
                return
            if self.crypto is not None:
                data = self.crypto.verify_frame(data)
                if data is None:
                    continue          # unauthenticated datagram: drop
            parsed = self.parser.decapsulate(data)
            if parsed is None:
                continue              # parser rejected the packet
            b, c = parsed
            sid = self._next_session
            self._next_session += 1
            self._sessions[sid] = ("udp", addr)
            self.inject(EXT_IN, a=sid, b=b, c=c)

    def _poll_tcp(self):
        if self.tcp is None:
            return
        while True:
            try:
                conn, addr = self.tcp.accept()
            except (BlockingIOError, OSError):
                break
            conn.setblocking(False)
            sid = self._next_session
            self._next_session += 1
            self._tcp_conns[sid] = (conn, bytearray())
            self._sessions[sid] = ("tcp", sid)
        dead = []
        for sid, (conn, buf) in self._tcp_conns.items():
            try:
                chunk = conn.recv(65536)
                if chunk == b"":
                    dead.append(sid)
                    continue
                buf.extend(chunk)
            except BlockingIOError:
                pass
            except OSError:
                dead.append(sid)
                continue
            # length-prefixed frames (SimpleTCP stream framing)
            while len(buf) >= 4:
                ln = int.from_bytes(buf[:4], "big")
                if len(buf) < 4 + ln:
                    break             # incomplete frame: wait for more
                # undersized frames fall through to the parser, which
                # rejects them (custom parsers may use smaller framing)
                frame = bytes(buf[4:4 + ln])
                del buf[:4 + ln]
                if self.crypto is not None:
                    frame = self.crypto.verify_frame(frame)
                    if frame is None:
                        continue      # unauthenticated frame: drop
                parsed = self.parser.decapsulate(frame)
                if parsed is None:
                    continue          # parser rejected the frame
                b, c = parsed
                self.inject(EXT_IN, a=sid, b=b, c=c)
        for sid in dead:
            self._tcp_conns.pop(sid, None)
            self._sessions.pop(sid, None)

    def _drain_ext_out(self):
        """Transmit socket-session EXT_OUT messages (raw-packet/tun
        sessions drain via TunBridge.collect_raw — the shared
        :func:`drain_ext_out` frees only what its handler consumed)."""

        def handler(sid, b, c):
            sess = self._sessions.get(sid)
            if sess is not None and sess[0] == "tun":
                return False          # not ours — leave for the bridge
            if sess is None:
                return True           # orphan: free, nothing to send
            payload = self.parser.encapsulate(sid, b, c)
            if self.crypto is not None:
                payload = self.crypto.sign_frame(payload)
            if sess[0] == "udp":
                try:
                    self.udp.sendto(payload, sess[1])
                except OSError:
                    pass
            else:
                entry = self._tcp_conns.get(sid)
                if entry is not None:
                    try:
                        entry[0].sendall(
                            len(payload).to_bytes(4, "big") + payload)
                    except OSError:
                        pass
            return True

        self.state = drain_ext_out(self.state, self.gw, handler)

    # ------------------------------------------------ the loop ---------
    def pump(self, sim_seconds: float = 0.1):
        """Poll sockets, inject, advance the simulation, transmit.

        Steps tick by tick and drains EXT_OUT *between* ticks — an
        EXT_OUT self-send would otherwise be delivered back into the
        gateway node's inbox (and consumed) on the very next tick."""
        self._poll_udp()
        self._poll_tcp()
        target = int(self.state.t_now) + int(sim_seconds * NS)
        while int(self.state.t_now) < target:
            prev = int(self.state.t_now)
            self.state = self.sim.step(self.state)
            self._drain_ext_out()
            for fn in self.ext_drains:
                fn()
            if int(self.state.t_now) == prev and not bool(
                    np.asarray(self.state.pool.valid).any()):
                break   # nothing scheduled anywhere: idle sim

    def run_realtime(self, duration_s: float, slice_s: float = 0.05):
        """Realtime pacing: simulated time tracks wall-clock time
        (realtimescheduler.cc waits on the socket until the next event)."""
        t0_wall = time.monotonic()
        t0_sim = int(self.state.t_now) / NS
        while True:
            elapsed = time.monotonic() - t0_wall
            if elapsed >= duration_s:
                return
            ahead = (int(self.state.t_now) / NS - t0_sim) - elapsed
            if ahead > slice_s:
                time.sleep(min(ahead, slice_s))
                continue
            self.pump(slice_s)

    def close(self):
        self.udp.close()
        if self.tcp is not None:
            self.tcp.close()
        for conn, _ in self._tcp_conns.values():
            try:
                conn.close()
            except OSError:
                pass
